"""PR-2 bytes-attribution pass: audit parser correctness, cost-analysis
regression gates, and the traffic knobs (--remat, --shard_update).

All inline-cheap (tier-1 870s budget): single-device programs except the
one 2-device shard_update parity run, small batches, small synthetic
splits.  The budget constants are the CPU-backend XLA cost-analysis
numbers recorded at PR 2 (this tree); the gates fail on >10% bytes growth
so a future change cannot silently re-inflate the step's memory traffic
(the round-5 LUT-gather tax hid in exactly this blind spot).
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributedtensorflowexample_tpu.data import DeviceDataset
from distributedtensorflowexample_tpu.data.synthetic import make_synthetic
from distributedtensorflowexample_tpu.models import build_model
from distributedtensorflowexample_tpu.parallel.sync import (
    make_indexed_train_step, make_train_step)
from distributedtensorflowexample_tpu.training.state import TrainState
from distributedtensorflowexample_tpu.utils.profiling import (
    bytes_audit, cost_and_bytes_audit, hlo_bytes_by_op)


_STEP_COST_MEMO: dict = {}


def _indexed_step_cost(model_name: str, momentum: float, lr: float,
                       batch: int = 64, rows: int = 2048):
    """Cost + audit of the device-resident indexed train step — the bench
    workloads' program shape (gather + dequant + train), single device.
    Memoized: the budget gate and the parser-agreement test share one
    compile (XLA compiles are the wall-time cost on the 1-core tier-1
    host, and lower().compile() bypasses the jit cache)."""
    key = (model_name, momentum, lr, batch, rows)
    if key in _STEP_COST_MEMO:
        return _STEP_COST_MEMO[key]
    x, y = make_synthetic(rows, (28, 28, 1), 10, seed=0)
    ds = DeviceDataset(np.asarray(x), np.asarray(y), batch, seed=0)
    model = build_model(model_name, dropout=0.5)
    tx = optax.sgd(lr, momentum=momentum) if momentum else optax.sgd(lr)
    state = TrainState.create(model, tx,
                              jnp.zeros((batch, 28, 28, 1), jnp.float32))
    step = make_indexed_train_step(batch, ds.steps_per_epoch,
                                   num_slots=ds.num_slots)
    _STEP_COST_MEMO[key] = cost_and_bytes_audit(step, (state, ds.peek()),
                                                unroll=1)
    return _STEP_COST_MEMO[key]


# CPU-backend XLA cost-analysis budgets recorded at PR 2 (batch 64,
# 2048-row synthetic split, uint8-resident + affine dequant, jax 0.4.37).
# bytes gate: >10% growth fails (the satellite contract); flops gate the
# same so a "free" optimization can't quietly add compute either.
_BUDGETS = {
    "mnist_cnn": {"flops": 4_787_992_064, "bytes": 410_183_520},
    "softmax": {"flops": 2_244_748, "bytes": 2_405_928},
}


@pytest.mark.parametrize("model_name,momentum,lr",
                         [("mnist_cnn", 0.9, 0.05), ("softmax", 0.0, 0.5)])
def test_cost_budget_gate(model_name, momentum, lr):
    cost, _ = _indexed_step_cost(model_name, momentum, lr)
    budget = _BUDGETS[model_name]
    assert cost, "CPU backend stopped reporting cost analysis"
    assert cost["bytes_accessed"] <= 1.10 * budget["bytes"], (
        f"{model_name} step bytes_accessed {cost['bytes_accessed']:.3e} "
        f"grew >10% over the recorded budget {budget['bytes']:.3e} — "
        "memory traffic regressed (or the budget needs a justified bump)")
    assert cost["flops"] <= 1.10 * budget["flops"]
    # Sanity floor: a 2x drop means the probe measured a different program
    # (e.g. the dequant or gather silently vanished), not a win.
    assert cost["bytes_accessed"] >= 0.5 * budget["bytes"]
    assert cost["flops"] >= 0.5 * budget["flops"]


def test_audit_total_matches_xla_cost_analysis():
    """The per-op parser must track XLA's own aggregate: its rows are a
    decomposition of `bytes accessed`, not an independent estimate.
    Agreement tightens with program size — <0.1% on the batch-256 ResNet
    step (BYTES_AUDIT_pr2_cpu.json) — but is not exact: HloCostAnalysis
    prices FUSION operands by per-element utilization while the parser
    prices them at full size, and broadcast/scalar traffic at 0.  15%
    holds headroom for this batch-64 program (measured +13%)."""
    cost, audit = _indexed_step_cost("mnist_cnn", 0.9, 0.05)
    assert audit and cost
    assert abs(audit["bytes_per_step"] - cost["bytes_accessed"]) \
        <= 0.15 * cost["bytes_accessed"]
    cats = audit["by_category_per_step"]
    assert "conv" in cats and cats["conv"] > 0
    assert audit["top_ops"] and audit["top_ops"][0]["bytes_per_step"] > 0
    # Rows are self-consistent with the summary.
    assert audit["bytes_per_step"] == round(sum(cats.values()))


def test_effective_bytes_reprice_resident_split_gather():
    """The cost convention charges the fused row gather for the WHOLE
    resident split; effective bytes re-price it at rows-touched.  The
    phantom must cover at least the split array itself — this is the
    artifact that inflated the round-5 on-chip ResNet record."""
    split_bytes = 2048 * 28 * 28 * 1      # uint8-resident
    _, audit = _indexed_step_cost("softmax", 0.0, 0.5)
    # >= 80% of the split: the reprice deducts (operand - output), and the
    # gather fusion's f32 output is a small fraction of the u8 split.
    assert audit["phantom_gather_bytes_per_step"] >= 0.8 * split_bytes
    assert (audit["bytes_effective_per_step"]
            <= audit["bytes_per_step"] - 0.8 * split_bytes)


def test_audit_unroll_weights_scan_body():
    """A K-step fused window (lax.scan -> while) must audit to the same
    per-step bytes as the plain step, up to the one-time entry overhead:
    the while body is weighted by the trip count, then normalized."""
    x, y = make_synthetic(1024, (28, 28, 1), 10, seed=0)

    def build(unroll):
        ds = DeviceDataset(np.asarray(x), np.asarray(y), 64, seed=0,
                           steps_per_next=unroll)
        model = build_model("softmax")
        state = TrainState.create(model, optax.sgd(0.5),
                                  jnp.zeros((64, 28, 28, 1), jnp.float32))
        step = make_indexed_train_step(64, ds.steps_per_epoch,
                                       unroll_steps=unroll,
                                       num_slots=ds.num_slots)
        _, audit = cost_and_bytes_audit(step, (state, ds.peek()),
                                        unroll=unroll)
        return audit

    one, eight = build(1), build(8)
    assert eight["bytes_effective_per_step"] == pytest.approx(
        one["bytes_effective_per_step"], rel=0.30)


def test_hlo_parser_on_synthetic_text():
    """Pure-text unit: shapes, weights and categories, no backend."""
    hlo = """
HloModule m

%fused_computation (p0: f32[8,4]) -> f32[8,4] {
  %p0 = f32[8,4]{1,0} parameter(0)
  ROOT %g = f32[8,4]{1,0} gather(f32[8,4]{1,0} %p0), offset_dims={1}
}

%body (p: s32[]) -> s32[] {
  %p = s32[] parameter(0)
  %big = f32[100]{0} add(f32[100]{0} %p, f32[100]{0} %p)
  ROOT %c = s32[] add(s32[] %p, s32[] %p)
}

%cond (p: s32[]) -> pred[] {
  %p = s32[] parameter(0)
  ROOT %ok = pred[] compare(s32[] %p, s32[] %p), direction=LT
}

%br_a (p: f32[50]) -> f32[50] {
  %p = f32[50]{0} parameter(0)
  ROOT %m = f32[50]{0} multiply(f32[50]{0} %p, f32[50]{0} %p)
}

%br_b (p: f32[50]) -> f32[50] {
  %p = f32[50]{0} parameter(0)
  ROOT %n = f32[50]{0} negate(f32[50]{0} %p)
}

ENTRY %main (a: f32[8,4]) -> f32[8,4] {
  %a = f32[8,4]{1,0} parameter(0)
  %w = s32[] while(s32[] %a), condition=%cond, body=%body
  %c = f32[50]{0} conditional(pred[] %a, f32[50]{0} %a, f32[50]{0} %a), true_computation=%br_a, false_computation=%br_b
  %conv = f32[8,4]{1,0} convolution(f32[8,4]{1,0} %a, f32[8,4]{1,0} %a)
  ROOT %f = f32[8,4]{1,0} fusion(f32[8,4]{1,0} %conv), kind=kLoop, calls=%fused_computation
}
"""
    rows = hlo_bytes_by_op(hlo, unroll=4)
    by_op = {r["name"]: r for r in rows}
    assert by_op["conv"]["category"] == "conv"
    assert by_op["conv"]["bytes"] == 3 * 8 * 4 * 4      # out + 2 operands
    assert by_op["f"]["category"] == "gather"           # fused gather
    # while body weighted by unroll: 100-float add = 3*400B, x4
    assert by_op["big"]["bytes"] == 3 * 400 * 4
    # conditional branches are visited (the lax.cond in the async step's
    # period-aligned averaging must not be a silent blind spot).
    assert by_op["m"]["bytes"] == 3 * 50 * 4    # out + 2 operands
    assert by_op["n"]["bytes"] == 2 * 50 * 4    # negate: out + 1 operand
    summary = bytes_audit(hlo, unroll=4)
    assert summary["bytes_per_step"] == round(
        sum(r["bytes"] for r in rows) / 4)


def test_flops_parser_on_synthetic_text():
    """Dot-general FLOP accounting, pure text: batch dims, contracting
    dims, fusion-internal dots at the fusion's weight, scan weighting —
    the golden pin for the MFU denominator (an attention einsum priced
    wrong would silently drift every MFU line)."""
    from distributedtensorflowexample_tpu.utils.profiling import (
        flops_audit, hlo_flops_by_op)
    hlo = """
HloModule m

%fused_dot (p0: f32[8,16]) -> f32[8,8] {
  %p0 = f32[8,16]{1,0} parameter(0)
  ROOT %fd = f32[8,8]{1,0} dot(f32[8,16]{1,0} %p0, f32[16,8]{1,0} %p0), lhs_contracting_dims={1}, rhs_contracting_dims={0}
}

%body (p: s32[]) -> s32[] {
  %p = s32[] parameter(0)
  %bdot = f32[2,4,16,16]{3,2,1,0} dot(f32[2,4,16,8]{3,2,1,0} %p, f32[2,4,8,16]{3,2,1,0} %p), lhs_batch_dims={0,1}, lhs_contracting_dims={3}, rhs_batch_dims={0,1}, rhs_contracting_dims={2}
  ROOT %c = s32[] add(s32[] %p, s32[] %p)
}

%cond (p: s32[]) -> pred[] {
  %p = s32[] parameter(0)
  ROOT %ok = pred[] compare(s32[] %p, s32[] %p), direction=LT
}

ENTRY %main (a: f32[4,4]) -> f32[4,4] {
  %a = f32[4,4]{1,0} parameter(0)
  %w = s32[] while(s32[] %a), condition=%cond, body=%body
  %conv = f32[1,8,8,32]{3,2,1,0} convolution(f32[1,8,8,16]{3,2,1,0} %a, f32[3,3,16,32]{3,2,1,0} %a), window={size=3x3 pad=1_1x1_1}, dim_labels=b01f_01io->b01f
  ROOT %f = f32[8,8]{1,0} fusion(f32[4,4]{1,0} %conv), kind=kLoop, calls=%fused_dot
}
"""
    rows = hlo_flops_by_op(hlo, unroll=4)
    by_name = {r["name"]: r for r in rows}
    # Batched dot inside the scan body: 2 * prod(out) * K, x unroll 4.
    assert by_name["bdot"]["flops"] == 2 * (2 * 4 * 16 * 16) * 8 * 4
    # Fusion-internal dot priced at the fusion's weight (1).
    assert by_name["fd"]["flops"] == 2 * (8 * 8) * 16
    assert by_name["fd"]["fusion"] == "f"
    # Convolution: 2 * out_elems * kh*kw*cin (kernel_elems / out_ch).
    assert by_name["conv"]["flops"] == 2 * (8 * 8 * 32) * (3 * 3 * 16)
    summary = flops_audit(hlo, unroll=4)
    assert summary["matmul_flops_per_step"] == round(
        (by_name["bdot"]["flops"] + by_name["fd"]["flops"]) / 4)
    assert summary["conv_flops_per_step"] == round(
        by_name["conv"]["flops"] / 4)
    assert summary["flops_per_step"] == (summary["matmul_flops_per_step"]
                                         + summary["conv_flops_per_step"])


def test_flops_audit_matches_xla_on_attention_einsum():
    """The compiled attention einsum's parsed flops equal both the
    analytic count AND XLA's own cost analysis — dot-generals with batch
    dims are priced exactly (the satellite fix: a batch dim mistaken for
    a contracting dim would square T into the count)."""
    from distributedtensorflowexample_tpu.utils.profiling import (
        flops_audit)
    B, T, H, Dh = 2, 16, 4, 8

    def att(q, k):
        return jnp.einsum("bthd,bshd->bhts", q, k)

    compiled = jax.jit(att).lower(
        jnp.zeros((B, T, H, Dh)), jnp.zeros((B, T, H, Dh))).compile()
    fa = flops_audit(compiled.as_text())
    assert fa["flops_per_step"] == 2 * B * H * T * T * Dh
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0]
    if ca and ca.get("flops"):
        assert fa["flops_per_step"] == int(ca["flops"])


def test_remat_block_is_bitwise_identical():
    """--remat block replays identical ops: loss, grads AND the BN stat
    updates must match the un-remat'd model BITWISE (no tolerance — the
    knob trades flops for activation residency, never values)."""
    rng = jax.random.PRNGKey(0)
    x = jax.random.normal(rng, (2, 32, 32, 3), jnp.float32)

    def run(remat):
        model = build_model("resnet20", remat=remat)
        variables = model.init({"params": rng, "dropout": rng}, x,
                               train=False)

        def loss_fn(params):
            out, upd = model.apply(
                {"params": params, "batch_stats": variables["batch_stats"]},
                x, train=True, mutable=["batch_stats"])
            return jnp.sum(out.astype(jnp.float32) ** 2), upd

        (loss, upd), grads = jax.jit(
            jax.value_and_grad(loss_fn, has_aux=True))(variables["params"])
        return loss, grads, upd

    l0, g0, u0 = run("none")
    l1, g1, u1 = run("block")
    assert np.asarray(l0).tobytes() == np.asarray(l1).tobytes()
    for a, b in zip(jax.tree.leaves((g0, u0)), jax.tree.leaves((g1, u1))):
        assert np.array_equal(np.asarray(a), np.asarray(b))


def test_remat_registry_and_validation():
    assert build_model("resnet20", remat="block").remat == "block"
    assert build_model("resnet20").remat == "none"
    with pytest.raises(ValueError, match="unknown remat"):
        build_model("resnet20", remat="bogus").init(
            jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)))


def test_shard_update_parity_and_layout():
    """--shard_update: same training math (allclose — the gradient
    all-reduce may legitimately become reduce-scatter + all-gather, which
    regroups the summation; observed bitwise-equal on this backend), and
    the optimizer state actually lives sharded (per-device momentum bytes
    ~1/D), which is the whole point (arXiv:2004.13336)."""
    from distributedtensorflowexample_tpu.parallel import (
        batch_sharding, make_mesh, replicated_sharding)
    from distributedtensorflowexample_tpu.training.optimizers import (
        cross_replica_update_sharding, update_shardings)

    mesh = make_mesh(2)
    model = build_model("softmax")
    rng = np.random.RandomState(0)
    batches = [{"image": rng.rand(8, 28, 28, 1).astype(np.float32),
                "label": rng.randint(0, 10, 8).astype(np.int32)}
               for _ in range(4)]

    def run(shard):
        tx = optax.sgd(0.1, momentum=0.9)
        if shard:
            tx = cross_replica_update_sharding(tx, mesh)
        state = TrainState.create_sharded(model, tx, (8, 28, 28, 1), 0,
                                          replicated_sharding(mesh))
        if shard:
            state = state.replace(opt_state=jax.device_put(
                state.opt_state, update_shardings(state.opt_state, mesh)))
        step = make_train_step(mesh=mesh)
        with mesh:
            for b in batches:
                state, _ = step(state, jax.device_put(
                    b, batch_sharding(mesh)))
        return state

    s0, s1 = run(False), run(True)
    for a, b in zip(jax.tree.leaves(s0.params), jax.tree.leaves(s1.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-6, atol=1e-7)
    # The momentum buffer for the [784, 10] kernel must be SHARDED.
    trace = jax.tree.leaves(s1.opt_state)
    big = max(trace, key=lambda l: l.size)
    assert not big.sharding.is_fully_replicated
    assert big.addressable_shards[0].data.size == big.size // 2


def test_shard_update_async_refused_by_name():
    """The trainer surface names the conflict instead of training a
    nonsensical layout: async state is worker-tiled (each device already
    owns its workers' whole update)."""
    from distributedtensorflowexample_tpu.config import RunConfig
    from distributedtensorflowexample_tpu.trainers.common import run_training

    cfg = RunConfig(sync_mode="async", shard_update=True,
                    dataset="synthetic", train_steps=2)
    with pytest.raises(ValueError, match="shard_update"):
        run_training(cfg, "softmax", "mnist")


def test_remat_flag_reaches_resnet_via_trainer_wiring():
    """--remat travels RunConfig -> build_model -> ResNetCIFAR (and is
    ignored gracefully by the other registry models)."""
    from distributedtensorflowexample_tpu.config import parse_flags

    cfg = parse_flags(["--remat", "block"])
    assert cfg.remat == "block"
    assert build_model("resnet20", remat=cfg.remat).remat == "block"
    build_model("mnist_cnn", remat=cfg.remat)      # no TypeError
    build_model("softmax", remat=cfg.remat)


def test_shard_update_flag_wiring():
    from distributedtensorflowexample_tpu.config import RunConfig
    from distributedtensorflowexample_tpu.training.optimizers import (
        build_optimizer)
    from distributedtensorflowexample_tpu.parallel import make_mesh

    with pytest.raises(ValueError, match="shard_update"):
        build_optimizer(RunConfig(shard_update=True, fused_optimizer=True,
                                  momentum=0.9, train_steps=10),
                        mesh=make_mesh(2))
    with pytest.raises(ValueError, match="mesh"):
        build_optimizer(RunConfig(shard_update=True, train_steps=10))
    # 1-extent data axis: wrapper is a no-op passthrough.
    from distributedtensorflowexample_tpu.training.optimizers import (
        cross_replica_update_sharding)
    tx = optax.sgd(0.1)
    assert cross_replica_update_sharding(tx, make_mesh(1)) is tx
