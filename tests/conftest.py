"""Test environment: 8 virtual CPU devices.

Replaces the reference's "4 processes on localhost with distinct ports"
trick (SURVEY.md §4): the real mesh/NamedSharding/psum code path runs
unchanged on fake CPU devices — no TPU needed for distribution tests.

Note: this image's sitecustomize force-registers the axon TPU platform and
overrides JAX_PLATFORMS from the environment, so the env-var route does not
work here — the config must be updated in-process before first backend use.
"""

import os

# In-process CPU collectives need every virtual device's thread in flight
# at once; on this 1-core host a missing thread hits XLA's rendezvous
# deadline, which ABORTS the process (rendezvous.cc "Expected 8 threads
# to join... only 7 arrived").  Must be in XLA_FLAGS before first backend
# use.
# Round-3 warning: an UNKNOWN name in XLA_FLAGS is a FATAL abort at first
# backend init, and pytest's capture eats the `F... Unknown flag` line —
# the symptom is rc=1 with ZERO output from the whole run.  Both names
# below are verified accepted by this jaxlib (tests/test_utils.py pins
# that a tiny backend-touching subprocess survives with exactly these
# flags).
# Round-3 finding (reproduced under 2 CPU hogs, 65-min run): the abort is
# a true DEADLOCK — a participant that never arrives — not transient
# starvation: with terminate=1800 s the run hung ~25 min inside ONE
# collective, then aborted anyway.  So the deadline is deliberately LOW
# (≈25x a loaded collective's normal latency: a deadlock should die in
# minutes), and the run_training-heavy files execute in isolated
# subprocesses with abort-only retry (tests/test_isolated.py) so one
# deadlock cannot kill the suite.
import jax
import pytest

from distributedtensorflowexample_tpu.compat import (
    cpu_collective_flags, enable_persistent_compilation_cache,
    set_num_cpu_devices)

# Version-gated through compat: 0.4.x jaxlibs don't know these names, and
# an unknown name is itself the fatal abort described above.  Importing
# jax before appending is safe — XLA_FLAGS is parsed at first BACKEND
# INIT, not at import.
if "--xla_cpu_collective_call" not in os.environ.get("XLA_FLAGS", ""):
    # idempotent: the isolated-subprocess inner runs inherit the outer
    # value and must not append duplicates
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + cpu_collective_flags(warn_s=60, terminate_s=300))

from isolation_list import ISOLATED_FILES

# The device-heavy files run via tests/test_isolated.py (subprocess +
# abort-only retry) in a full-suite run; DISTTF_INNER_PYTEST=1 marks the
# inner invocation, which collects them normally.
if os.environ.get("DISTTF_INNER_PYTEST") != "1":
    collect_ignore = list(ISOLATED_FILES)

jax.config.update("jax_platforms", "cpu")
# 8 virtual devices normally.  DISTTF_TEST_DEVICES overrides: the
# isolation wrapper retries an ABORTED inner run at 4 devices — same
# mesh/psum/sharding code path, narrower rendezvous, which drops the
# under-contention deadlock probability that caused the abort.
# Through the compat shim: current jax has the jax_num_cpu_devices
# config, the 0.4.x pin only honors the XLA force-host-device flag.
set_num_cpu_devices(int(os.environ.get("DISTTF_TEST_DEVICES", "8")))
# Persistent compilation cache: the suite is compile-dominated (dozens of
# jit programs, recompiled from scratch in every isolated subprocess —
# tests/test_isolated.py), and this 1-core host pays ~30-80 s per big
# compile under load.  The cache is keyed by HLO+flags+topology, so the
# 8-virtual-device programs hit across inner runs and across consecutive
# suite runs.  VERSION-GATED through compat: on 0.4.x jaxlibs a
# cache-loaded executable silently drops donated-argument write-backs
# (BN stats come back unchanged from a hit), so there the helper is a
# no-op and each process recompiles.
enable_persistent_compilation_cache(
    os.environ.get("DISTTF_JAX_CACHE", "/tmp/jax_cache_tests"))
# Synchronous CPU dispatch: a deep async queue of collective programs
# multiplies the concurrent-thread demand and with it the starvation
# window.  Purely a test-environment knob — the TPU runtime throttles its
# own queue.
jax.config.update("jax_cpu_enable_async_dispatch", False)


def pytest_collection_modifyitems(config, items):
    """Run the isolated-subprocess wrappers (tests/test_isolated.py) LAST.
    Each wrapper is a full pytest subprocess that recompiles every jit
    program from scratch (no trustworthy persistent cache on the 0.4.x
    pin — see compat.enable_persistent_compilation_cache), so on a loaded
    1-core host they dominate wall time by minutes per file.  Running the
    cheap inline tests first means a time-bounded suite run (the tier-1
    harness kills at a fixed deadline) reports every fast test's verdict
    instead of losing them behind a mid-alphabet compile stall."""
    items.sort(key=lambda it: it.fspath.basename == "test_isolated.py")


@pytest.fixture()
def tmp_log_dir(tmp_path):
    return str(tmp_path / "logs")


@pytest.fixture()
def small_synthetic(monkeypatch):
    """Shrink the synthetic fallback splits: the device-resident path
    replicates the whole split per virtual device, and full-size programs
    on the 1-core CI host stretch XLA:CPU's 8-thread collective rendezvous
    past its hard timeout (flaky aborts).  Semantics under test don't
    depend on split size."""
    from distributedtensorflowexample_tpu.data import cifar10, mnist
    monkeypatch.setattr(mnist, "_SYNTH_SIZES", {"train": 2048, "test": 512})
    monkeypatch.setattr(cifar10, "_SYNTH_SIZES",
                        {"train": 2048, "test": 512})
