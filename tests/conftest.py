"""Test environment: 8 virtual CPU devices.

Replaces the reference's "4 processes on localhost with distinct ports"
trick (SURVEY.md §4): the real mesh/NamedSharding/psum code path runs
unchanged on fake CPU devices — no TPU needed for distribution tests.

Note: this image's sitecustomize force-registers the axon TPU platform and
overrides JAX_PLATFORMS from the environment, so the env-var route does not
work here — the config must be updated in-process before first backend use.
"""

import jax
import pytest

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)
# Synchronous CPU dispatch: with 8 virtual devices on few cores, a deep
# async queue of collective programs can deadlock XLA:CPU's rendezvous
# (observed with the zero-host-work device-resident input path, which lets
# the queue grow unboundedly).  Purely a test-environment knob — the TPU
# runtime throttles its own queue.
jax.config.update("jax_cpu_enable_async_dispatch", False)


@pytest.fixture()
def tmp_log_dir(tmp_path):
    return str(tmp_path / "logs")
