"""Test environment: 8 virtual CPU devices.

Replaces the reference's "4 processes on localhost with distinct ports"
trick (SURVEY.md §4): the real mesh/NamedSharding/psum code path runs
unchanged on fake CPU devices — no TPU needed for distribution tests.

Note: this image's sitecustomize force-registers the axon TPU platform and
overrides JAX_PLATFORMS from the environment, so the env-var route does not
work here — the config must be updated in-process before first backend use.
"""

import os

# In-process CPU collectives need every virtual device's thread in flight
# at once; on this 1-core host a starved thread can miss XLA's default
# 40-second rendezvous deadline, which ABORTS the process (rendezvous.cc
# "Expected 8 threads to join... only 7 arrived").  Raise the deadline so
# starvation waits instead of killing the test run.  Must be in XLA_FLAGS
# before first backend use.
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_cpu_collective_call_warn_stuck_timeout_seconds=120"
    + " --xla_cpu_collective_call_terminate_timeout_seconds=600")

import jax
import pytest

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)
# Synchronous CPU dispatch: a deep async queue of collective programs
# multiplies the concurrent-thread demand and with it the starvation
# window.  Purely a test-environment knob — the TPU runtime throttles its
# own queue.
jax.config.update("jax_cpu_enable_async_dispatch", False)


@pytest.fixture()
def tmp_log_dir(tmp_path):
    return str(tmp_path / "logs")


@pytest.fixture()
def small_synthetic(monkeypatch):
    """Shrink the synthetic fallback splits: the device-resident path
    replicates the whole split per virtual device, and full-size programs
    on the 1-core CI host stretch XLA:CPU's 8-thread collective rendezvous
    past its hard timeout (flaky aborts).  Semantics under test don't
    depend on split size."""
    from distributedtensorflowexample_tpu.data import cifar10, mnist
    monkeypatch.setattr(mnist, "_SYNTH_SIZES", {"train": 2048, "test": 512})
    monkeypatch.setattr(cifar10, "_SYNTH_SIZES",
                        {"train": 2048, "test": 512})
