"""Test environment: 8 virtual CPU devices.

Replaces the reference's "4 processes on localhost with distinct ports"
trick (SURVEY.md §4): the real mesh/NamedSharding/psum code path runs
unchanged on fake CPU devices — no TPU needed for distribution tests.

Note: this image's sitecustomize force-registers the axon TPU platform and
overrides JAX_PLATFORMS from the environment, so the env-var route does not
work here — the config must be updated in-process before first backend use.
"""

import os

# In-process CPU collectives need every virtual device's thread in flight
# at once; on this 1-core host a missing thread hits XLA's rendezvous
# deadline, which ABORTS the process (rendezvous.cc "Expected 8 threads
# to join... only 7 arrived").  Must be in XLA_FLAGS before first backend
# use.
# Round-3 warning: an UNKNOWN name in XLA_FLAGS is a FATAL abort at first
# backend init, and pytest's capture eats the `F... Unknown flag` line —
# the symptom is rc=1 with ZERO output from the whole run.  Both names
# below are verified accepted by this jaxlib (tests/test_utils.py pins
# that a tiny backend-touching subprocess survives with exactly these
# flags).
# Round-3 finding (reproduced under 2 CPU hogs, 65-min run): the abort is
# a true DEADLOCK — a participant that never arrives — not transient
# starvation: with terminate=1800 s the run hung ~25 min inside ONE
# collective, then aborted anyway.  So the deadline is deliberately LOW
# (≈25x a loaded collective's normal latency: a deadlock should die in
# minutes), and the run_training-heavy files execute in isolated
# subprocesses with abort-only retry (tests/test_isolated.py) so one
# deadlock cannot kill the suite.
if "--xla_cpu_collective_call" not in os.environ.get("XLA_FLAGS", ""):
    # idempotent: the isolated-subprocess inner runs inherit the outer
    # value and must not append duplicates
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_cpu_collective_call_warn_stuck_timeout_seconds=60"
        + " --xla_cpu_collective_call_terminate_timeout_seconds=300")

import jax
import pytest

from isolation_list import ISOLATED_FILES

# The device-heavy files run via tests/test_isolated.py (subprocess +
# abort-only retry) in a full-suite run; DISTTF_INNER_PYTEST=1 marks the
# inner invocation, which collects them normally.
if os.environ.get("DISTTF_INNER_PYTEST") != "1":
    collect_ignore = list(ISOLATED_FILES)

jax.config.update("jax_platforms", "cpu")
# 8 virtual devices normally.  DISTTF_TEST_DEVICES overrides: the
# isolation wrapper retries an ABORTED inner run at 4 devices — same
# mesh/psum/sharding code path, narrower rendezvous, which drops the
# under-contention deadlock probability that caused the abort.
jax.config.update("jax_num_cpu_devices",
                  int(os.environ.get("DISTTF_TEST_DEVICES", "8")))
# Persistent compilation cache: the suite is compile-dominated (dozens of
# jit programs, recompiled from scratch in every isolated subprocess —
# tests/test_isolated.py), and this 1-core host pays ~30-80 s per big
# compile under load.  The cache is keyed by HLO+flags+topology, so the
# 8-virtual-device programs hit across inner runs and across consecutive
# suite runs.
jax.config.update("jax_compilation_cache_dir",
                  os.environ.get("DISTTF_JAX_CACHE", "/tmp/jax_cache_tests"))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
# Synchronous CPU dispatch: a deep async queue of collective programs
# multiplies the concurrent-thread demand and with it the starvation
# window.  Purely a test-environment knob — the TPU runtime throttles its
# own queue.
jax.config.update("jax_cpu_enable_async_dispatch", False)


@pytest.fixture()
def tmp_log_dir(tmp_path):
    return str(tmp_path / "logs")


@pytest.fixture()
def small_synthetic(monkeypatch):
    """Shrink the synthetic fallback splits: the device-resident path
    replicates the whole split per virtual device, and full-size programs
    on the 1-core CI host stretch XLA:CPU's 8-thread collective rendezvous
    past its hard timeout (flaky aborts).  Semantics under test don't
    depend on split size."""
    from distributedtensorflowexample_tpu.data import cifar10, mnist
    monkeypatch.setattr(mnist, "_SYNTH_SIZES", {"train": 2048, "test": 512})
    monkeypatch.setattr(cifar10, "_SYNTH_SIZES",
                        {"train": 2048, "test": 512})
