"""The ledger-driven control plane (resilience/scheduler.py +
tools/schedule.py): cost-priced admission, priority packing, loss-free
SLO eviction, elastic shrink/grow policy, bounded retry + quarantine,
write-ahead journal replay after a SIGKILL, and the obs_query `why`
verb that answers for every decision from ledger rows alone.

Inline on purpose: every gang child here is a stdlib-only script
(milliseconds each, no jax import), so the whole file's verdicts land
inside the tier-1 budget.  The jax-heavy end-to-end drill (faultline
jobs, bitwise eviction-resume parity) lives in tests/test_sched_drill.py,
which runs as an isolated subprocess (tests/isolation_list.py).
"""

import json
import os
import subprocess
import sys
import textwrap
import time

import pytest

from distributedtensorflowexample_tpu.resilience.faults import (
    FaultInjectionHook, FaultPlan, FaultSpec, mark_host_down)
from distributedtensorflowexample_tpu.resilience.fleet import FleetSupervisor
from distributedtensorflowexample_tpu.resilience.scheduler import (
    SCHED_EVENTS, Job, Scheduler, load_queue, predict_cost,
    slo_priorities, tick_default)
from distributedtensorflowexample_tpu.resilience.supervisor import (
    Journal, RetryPolicy)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.sched


def _sched(tmp_path, jobs, **kw):
    kw.setdefault("devices", 2)
    kw.setdefault("workdir", str(tmp_path / "sched"))
    kw.setdefault("tick_s", 0.05)
    kw.setdefault("poll_s", 0.02)
    kw.setdefault("seed", 0)
    kw.setdefault("retry_policy", RetryPolicy(retries=10**6,
                                              backoff_base_s=0.05,
                                              backoff_max_s=0.1))
    return Scheduler(jobs, **kw)


def _ledger_rows(tmp_path) -> list[dict]:
    with open(tmp_path / "sched" / "RUNS.jsonl") as f:
        return [json.loads(line) for line in f if line.strip()]


def _sched_rows(tmp_path, job=None, event=None) -> list[dict]:
    rows = [r for r in _ledger_rows(tmp_path)
            if str(r.get("event", "")).startswith("sched_")]
    if job is not None:
        rows = [r for r in rows if r.get("job") == job]
    if event is not None:
        rows = [r for r in rows if r.get("event") == event]
    return rows


def _script(tmp_path, name, body) -> str:
    path = tmp_path / name
    path.write_text(textwrap.dedent(body))
    return str(path)


# ---- job description + env knobs ----------------------------------------

def test_job_validation_is_loud(tmp_path):
    with pytest.raises(ValueError, match="unknown field"):
        Job.from_dict({"job": "a", "argv": ["x"], "prioritee": 1})
    with pytest.raises(ValueError, match="ranks"):
        Job(job="a", argv=["x"], ranks=0)
    with pytest.raises(ValueError, match="path-safe"):
        Job(job="a/b", argv=["x"])
    with pytest.raises(ValueError, match="path-safe"):
        Job(job="..", argv=["x"])    # must not escape the jobs/ dir
    with pytest.raises(ValueError, match="duplicate"):
        _sched(tmp_path, [Job(job="a", argv=["x"]),
                          Job(job="a", argv=["y"])])


def test_load_queue_accepts_both_shapes(tmp_path):
    path = tmp_path / "q.json"
    path.write_text(json.dumps([{"job": "a", "argv": ["x"]}]))
    assert [j.job for j in load_queue(str(path))] == ["a"]
    path.write_text(json.dumps({"jobs": [{"job": "b", "argv": ["x"]}]}))
    assert [j.job for j in load_queue(str(path))] == ["b"]


def test_slo_priorities_env_override(monkeypatch):
    monkeypatch.delenv("SCHED_SLO_PRIORITIES", raising=False)
    base = slo_priorities()
    assert base["serve"] < base["train"] < base["bench"] < base["drill"]
    monkeypatch.setenv("SCHED_SLO_PRIORITIES", "bench=5, custom=1, bad")
    out = slo_priorities()
    assert out["bench"] == 5 and out["custom"] == 1
    assert out["serve"] == base["serve"]        # defaults survive
    # a job's explicit priority beats the kind table
    assert Job(job="a", argv=["x"], kind="bench",
               priority=2).resolved_priority(out) == 2
    assert Job(job="b", argv=["x"], kind="bench").resolved_priority(out) == 5


def test_tick_env_knob(monkeypatch):
    monkeypatch.delenv("SCHED_TICK_S", raising=False)
    assert tick_default() == 0.25
    monkeypatch.setenv("SCHED_TICK_S", "0.5")
    assert tick_default() == 0.5
    monkeypatch.setenv("SCHED_TICK_S", "bogus")
    assert tick_default() == 0.25


# ---- the cost model ------------------------------------------------------

def test_predict_cost_trajectory_then_declared(tmp_path):
    traj = tmp_path / "BENCH_trajectory.json"
    traj.write_text(
        json.dumps({"family": "BENCH_lm_cpu", "round": 8,
                    "file": "BENCH_lm_cpu_r08.json",
                    "metrics": {"lm_steps_per_sec": 4.0,
                                "lm_small_steps_per_sec": 2.0}}) + "\n"
        + json.dumps({"family": "BENCH_lm_cpu", "round": 12,
                      "file": "BENCH_lm_cpu_r12.json",
                      "metrics": {"lm_steps_per_sec": 8.0}}) + "\n")
    job = Job(job="a", argv=["x"], family="lm_cpu", steps=16,
              est_step_time_s=9.0)
    cost = predict_cost(job, str(traj))
    # newest round wins, measured beats declared
    assert cost["source"] == "trajectory:BENCH_lm_cpu_r12.json"
    assert cost["step_time_s"] == pytest.approx(1 / 8.0)
    assert cost["predicted_s"] == pytest.approx(2.0)
    # conservative: the SLOWEST rate of the newest row prices the job
    old = predict_cost(Job(job="b", argv=["x"], family="lm_cpu",
                           steps=2), str(tmp_path / "nope.json"))
    assert old["source"] is None and old["predicted_s"] is None
    declared = predict_cost(job, "")
    assert declared["source"] == "declared"
    assert declared["predicted_s"] == pytest.approx(144.0)


def test_admission_refusals(tmp_path):
    """Unplaceable width and over-ceiling cost refuse at admission —
    ledger rows say why, and the queue still drains."""
    py = sys.executable
    jobs = [Job(job="wide", argv=[py, "-c", "pass"], ranks=3),
            Job(job="costly", argv=[py, "-c", "pass"],
                steps=100, est_step_time_s=10.0),
            Job(job="ok", argv=[py, "-c", "pass"])]
    summary = _sched(tmp_path, jobs, max_job_s=60.0).run()
    assert summary["jobs"] == {"wide": "refused", "costly": "refused",
                               "ok": "done"}
    refuse = {r["job"]: r for r in _sched_rows(tmp_path,
                                               event="sched_refuse")}
    assert "mesh has 2" in refuse["wide"]["why"]
    assert "exceeds the per-job ceiling" in refuse["costly"]["why"]
    assert refuse["costly"]["predicted_s"] == pytest.approx(1000.0)


# ---- the 8-job mixed-queue acceptance (stdlib children) ------------------

def _victim_script(tmp_path, iters=10, sleep=0.15):
    """A long 'bench' job with resumable progress: each loop appends one
    line and sleeps; SIGTERM = save-and-exit-143 (the 143 protocol in
    miniature).  The progress file is the zero-lost-steps witness: the
    resumed run continues at exactly the next index, so a lost or
    repeated step shows up as a gap or duplicate line."""
    return _script(tmp_path, "victim.py", f"""
        import os, signal, sys, time
        prog = os.environ["PROG"]
        def term(s, f):
            sys.exit(143)
        signal.signal(signal.SIGTERM, term)
        while True:
            n = sum(1 for _ in open(prog)) if os.path.exists(prog) else 0
            if n >= {iters}:
                sys.exit(0)
            with open(prog, "a") as f:
                f.write(f"i{{n}}\\n")
            time.sleep({sleep})
    """)


def test_mixed_queue_acceptance_evict_retry_quarantine(tmp_path):
    """The 8-job mixed queue, inline: quick trains, a crash-retry job,
    a wedged job (quarantined, not requeued), an unplaceable job
    (refused), and a slow bench job a late-ready priority-0 'serve'
    job evicts loss-free — zero manual intervention, every decision a
    ledger row, and `obs_query why` explains the eviction after the
    fact from the ledger alone."""
    py = sys.executable
    prog = str(tmp_path / "progress")
    crash_marker = str(tmp_path / "crashed_once")
    victim = _victim_script(tmp_path)
    crashy = _script(tmp_path, "crashy.py", """
        import os, sys
        m = os.environ["MARKER"]
        if not os.path.exists(m):
            open(m, "w").close()
            os.kill(os.getpid(), 9)    # hard loss on the first placement
        sys.exit(0)
    """)
    jobs = [
        Job(job="t1", argv=[py, "-c", "pass"], kind="train"),
        Job(job="t2", argv=[py, "-c", "pass"], kind="train"),
        Job(job="t3", argv=[py, "-c", "pass"], kind="train"),
        # killed mid-queue on its first placement; the scheduler's
        # bounded retry (fleet_retries=0 pushes it up a level) requeues
        # it with backoff and the second placement completes.
        Job(job="kill1", argv=[py, crashy], kind="train", retries=2,
            fleet_retries=0, env={"MARKER": crash_marker}),
        Job(job="wedge1", argv=[py, "-c", "import sys; sys.exit(3)"],
            kind="drill", retries=3),
        Job(job="wide1", argv=[py, "-c", "pass"], ranks=3, kind="train"),
        Job(job="bench1", argv=[py, victim], kind="bench",
            env={"PROG": prog}),
        # ready the moment bench1 proves mid-run progress; needs the
        # whole mesh, so admission must evict.
        Job(job="serve1", argv=[py, "-c", "pass"], kind="serve",
            ranks=2, after_file=prog),
    ]
    summary = _sched(tmp_path, jobs).run()
    assert summary["jobs"] == {
        "t1": "done", "t2": "done", "t3": "done", "kill1": "done",
        "wedge1": "quarantined", "wide1": "refused",
        "bench1": "done", "serve1": "done"}
    assert summary["status"] == "degraded"      # the quarantine
    # bench1 is evicted exactly once; under CI contention a second
    # still-running low-priority job may legally be co-evicted
    assert summary["evictions"] >= 1
    assert len(_sched_rows(tmp_path, job="bench1",
                           event="sched_evict")) == 1

    # zero lost steps, zero repeated steps: the progress tape is exact
    lines = open(prog).read().split()
    assert lines == [f"i{i}" for i in range(10)]

    # every decision is a ledger row
    evict = _sched_rows(tmp_path, job="bench1", event="sched_evict")
    assert len(evict) == 1
    assert evict[0]["for_job"] == "serve1" and evict[0]["clean"] is True
    assert evict[0]["rcs"] == {"0": 143}
    retry = _sched_rows(tmp_path, job="kill1", event="sched_retry")
    assert retry and retry[0]["retry"] == 1
    quarantine = _sched_rows(tmp_path, job="wedge1",
                             event="sched_quarantine")
    assert quarantine and "wedged" in quarantine[0]["why"]
    # quarantined means NOT requeued: exactly one placement
    assert len(_sched_rows(tmp_path, job="wedge1",
                           event="sched_place")) == 1
    done_rows = _sched_rows(tmp_path, event="sched_done")
    assert {r["job"] for r in done_rows} == {"t1", "t2", "t3", "kill1",
                                             "bench1", "serve1"}
    qdone = _sched_rows(tmp_path, event="sched_queue_done")
    assert qdone and qdone[-1]["counts"]["done"] == 6

    # the WAL balances: every intent seq has a matching applied record
    events = Journal(str(tmp_path / "sched" / "sched.jsonl")).events()
    intents = {e["seq"] for e in events if e["event"] == "sched_intent"}
    applied = {e.get("seq") for e in events
               if e["event"].startswith("sched_")
               and e["event"] != "sched_intent"}
    assert intents <= applied

    # obs_query why: the preemption is answerable from ledger rows alone
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import obs_query
    finally:
        sys.path.pop(0)
    import io
    from contextlib import redirect_stdout
    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = obs_query.main(["why", "bench1", "--ledger",
                             str(tmp_path / "sched" / "RUNS.jsonl")])
    out = buf.getvalue()
    assert rc == 0
    assert "EVICTED" in out and "`serve1`" in out
    assert "preempted 1x (for `serve1`)" in out
    assert "finally completed" in out
    # prefix resolution + the not-found refusal
    buf = io.StringIO()
    with redirect_stdout(buf):
        assert obs_query.main(["why", "wedge", "--ledger",
                               str(tmp_path / "sched" /
                                   "RUNS.jsonl")]) == 0
    assert "QUARANTINED" in buf.getvalue()
    with pytest.raises(SystemExit, match="not found"):
        obs_query.main(["why", "nope", "--ledger",
                        str(tmp_path / "sched" / "RUNS.jsonl")])


# ---- elastic shrink + grow as scheduler policy ---------------------------

def test_scheduler_shrink_then_grow_policy(tmp_path):
    """host_loss shape end-to-end at the policy level (stdlib child
    standing in for the faultline drill): rank 1's host dies on the
    first gang attempt (tombstone + SIGKILL), the elastic gang shrinks
    and keeps running; when the tombstone expires the scheduler's
    recovery probe cleanly stops the job (TERM→143) and relaunches it
    at FULL width — sched_shrink and sched_grow rows tell the story."""
    py = sys.executable
    child = _script(tmp_path, "elastic.py", """
        import json, os, signal, sys, time
        rank = int(os.environ["OBS_RANK"])
        n = int(os.environ["FLEET_NUM_RANKS"])
        attempt = int(os.environ["SUPERVISE_ATTEMPT"])
        print(json.dumps({"rank": rank, "n": n}), flush=True)
        if attempt == 0 and n == 2 and rank == 1 \\
                and not os.path.exists(os.environ["ONCE"]):
            open(os.environ["ONCE"], "w").close()
            with open(os.environ["FLEET_HOST_DOWN_FILE"], "w") as f:
                json.dump({"ts": time.time(), "down_s": 1.2}, f)
            os.kill(os.getpid(), 9)
        if n == 1:
            # shrunken: keep "training" until the grow-stop's TERM
            signal.signal(signal.SIGTERM, lambda s, f: sys.exit(143))
            time.sleep(30)
            sys.exit(1)
        sys.exit(0)
    """)
    jobs = [Job(job="el", argv=[py, child], kind="train", ranks=2,
                elastic=True, fleet_retries=4,
                env={"ONCE": str(tmp_path / "once")})]
    summary = _sched(tmp_path, jobs).run()
    assert summary["jobs"] == {"el": "done"}
    assert summary["shrinks"] >= 1 and summary["grows"] >= 1
    shrink = _sched_rows(tmp_path, job="el", event="sched_shrink")
    assert shrink and shrink[0]["ranks"] == 1 and shrink[0]["lost"] == [1]
    grow = _sched_rows(tmp_path, job="el", event="sched_grow")
    assert any(g.get("recovered") == [1] for g in grow)
    # the final placement ran at full width again
    place = _sched_rows(tmp_path, job="el", event="sched_place")
    assert place[-1]["ranks"] == 2 and place[-1]["resumed"] is True
    done = _sched_rows(tmp_path, job="el", event="sched_done")
    assert done and done[0]["rcs"] == {"0": 0, "1": 0}


# ---- anomaly detections feed eviction policy (ROADMAP direction 5) -------

def test_straggling_job_yields_to_queued_healthy_job(tmp_path):
    """The heal rung: a 2-rank bench job whose rank 1 is NAMED
    straggler by the fleet's monitor (lag + its own regression flag —
    health files written by the children themselves, the detect_skew
    contract) is evicted by the ANOMALY policy so an EQUAL-priority
    queued train job gets the mesh — plain SLO preemption could never
    justify this eviction (it only fires on strictly-less-urgent
    victims), so the sched_evict row's why names the straggler.  The
    bench requeues uncharged, relaunches clean (marker file drops the
    straggle), and its progress tape is gap- and duplicate-free."""
    py = sys.executable
    prog = str(tmp_path / "progress")
    child = _script(tmp_path, "strag.py", """
        import json, os, signal, sys, time
        signal.signal(signal.SIGTERM, lambda s, f: sys.exit(143))
        rank = int(os.environ["OBS_RANK"])
        hp = os.environ["OBS_HEALTH"]
        prog = os.environ["PROG"]
        once = os.environ["ONCE"] + f".r{rank}"
        straggle = not os.path.exists(once)
        open(once, "w").close()

        def health(step, firing, ewma):
            payload = {
                "version": 1, "kind": "rank", "rank": rank,
                "step": step, "updated_unix": time.time(),
                "flags": {"step_time_regression":
                          {"firing": firing,
                           "fired_step": 3 if firing else None},
                          "nan_loss": {"firing": False,
                                       "fired_step": None},
                          "loss_plateau": {"firing": False,
                                           "fired_step": None}},
                "detectors": {"step_time": {"ewma_s": ewma}}}
            tmp = hp + ".tmp"
            with open(tmp, "w") as f:
                json.dump(payload, f)
            os.replace(tmp, hp)

        for i in range(200):
            if rank == 0:
                # healthy front rank: advance + tape progress
                n = (sum(1 for _ in open(prog))
                     if os.path.exists(prog) else 0)
                if n >= 10:
                    health(100 + n, False, 0.01)
                    time.sleep(5)       # wait for the gang's fate
                    sys.exit(0)
                with open(prog, "a") as f:
                    f.write(f"i{n}\\n")
                health(10 + n, False, 0.01)
            elif straggle:
                # frozen at step 2 with its own regression firing
                health(2, True, 2.0)
            else:
                health(100 + i, False, 0.01)
                if i > 10:
                    sys.exit(0)
            time.sleep(0.1)
        sys.exit(0)
    """)
    jobs = [
        Job(job="bench1", argv=[py, child], kind="bench", ranks=2,
            fleet_retries=0, retries=2,
            env={"PROG": prog, "ONCE": str(tmp_path / "once")}),
        # equal priority, pinned: only the anomaly policy can evict
        # for this job — the SLO evictor needs strictly-lower urgency.
        Job(job="train1", argv=[py, "-c", "pass"], kind="train",
            priority=20, ranks=2),
    ]
    summary = _sched(tmp_path, jobs).run()
    assert summary["jobs"] == {"bench1": "done", "train1": "done"}
    evict = _sched_rows(tmp_path, job="bench1", event="sched_evict")
    assert len(evict) == 1 and evict[0]["for_job"] == "train1"
    assert "straggler" in evict[0]["why"]
    assert evict[0]["clean"] is True            # TERM→143, loss-free
    heal = [r for r in _ledger_rows(tmp_path)
            if str(r.get("event", "")).startswith("heal_")]
    kinds = [r["event"] for r in heal]
    assert "heal_detect" in kinds and "heal_evict" in kinds
    detect = next(r for r in heal if r["event"] == "heal_detect")
    assert detect["job"] == "bench1" and detect["kind"] == "straggler"
    he = next(r for r in heal if r["event"] == "heal_evict")
    assert he["detail"]["for_job"] == "train1"
    # the victim's tape is exact across the eviction: nothing lost,
    # nothing repeated
    assert open(prog).read().split() == [f"i{i}" for i in range(10)]
    # obs_query why folds both row families into one story
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import obs_query
    finally:
        sys.path.pop(0)
    import io
    from contextlib import redirect_stdout
    buf = io.StringIO()
    with redirect_stdout(buf):
        assert obs_query.main(["why", "bench1", "--ledger",
                               str(tmp_path / "sched"
                                   / "RUNS.jsonl")]) == 0
    out = buf.getvalue()
    assert "anomaly detected: straggler" in out
    assert "HEALED by eviction" in out
    assert "self-healed 1x (evict)" in out


def test_heal_dry_run_detects_but_never_evicts(tmp_path, monkeypatch):
    """HEAL_DRY_RUN: the same straggling gang is DETECTED (heal_detect
    + heal_dry_run rows) but nothing stops it — the bench runs to its
    own completion and the queued job simply waits."""
    monkeypatch.setenv("HEAL_DRY_RUN", "1")
    py = sys.executable
    child = _script(tmp_path, "strag_dry.py", """
        import json, os, signal, sys, time
        signal.signal(signal.SIGTERM, lambda s, f: sys.exit(143))
        rank = int(os.environ["OBS_RANK"])
        hp = os.environ["OBS_HEALTH"]
        t0 = time.time()
        i = 0
        while time.time() - t0 < 4.0:
            payload = {
                "version": 1, "kind": "rank", "rank": rank,
                "step": (2 if rank else 50 + i),
                "updated_unix": time.time(),
                "flags": {"step_time_regression":
                          {"firing": rank == 1, "fired_step":
                           2 if rank == 1 else None},
                          "nan_loss": {"firing": False,
                                       "fired_step": None},
                          "loss_plateau": {"firing": False,
                                           "fired_step": None}},
                "detectors": {"step_time":
                              {"ewma_s": 2.0 if rank else 0.01}}}
            tmp = hp + ".tmp"
            with open(tmp, "w") as f:
                json.dump(payload, f)
            os.replace(tmp, hp)
            i += 1
            time.sleep(0.1)
        sys.exit(0)
    """)
    jobs = [
        Job(job="bench1", argv=[py, child], kind="bench", ranks=2,
            fleet_retries=0),
        Job(job="train1", argv=[py, "-c", "pass"], kind="train",
            priority=20, ranks=2),
    ]
    summary = _sched(tmp_path, jobs).run()
    assert summary["jobs"] == {"bench1": "done", "train1": "done"}
    assert not _sched_rows(tmp_path, job="bench1", event="sched_evict")
    heal = [r["event"] for r in _ledger_rows(tmp_path)
            if str(r.get("event", "")).startswith("heal_")]
    assert "heal_detect" in heal and "heal_dry_run" in heal
    assert "heal_evict" not in heal


def test_heal_intent_replay_before_any_job_runs_is_clean_noop(tmp_path):
    """A scheduler SIGKILLed between the remediator's heal_intent and
    its applied row: the next incarnation re-applies the intent during
    construction, through _heal_evict, while every job is still queued
    — the documented idempotent noop ("job not running"), never an
    error row from half-initialized scheduler state."""
    workdir = tmp_path / "sched"
    workdir.mkdir(parents=True)
    dead = Journal(str(workdir / "sched.jsonl"))
    dead.write("heal_detect", key="a:l0:straggler:rank0",
               kind="straggler", job="a")
    dead.write("heal_intent", seq=1, action="evict",
               key="a:l0:straggler:rank0", kind="straggler", job="a")
    sched = _sched(tmp_path, [Job(job="a", argv=[sys.executable,
                                                 "-c", "pass"])])
    heal = [r for r in sched.journal.events()
            if str(r.get("event", "")).startswith("heal_")]
    assert not any(r.get("error") for r in heal)
    sup = [r for r in heal if r["event"] == "heal_suppressed"]
    assert sup and sup[-1]["reason"].startswith("noop")


# ---- write-ahead journal: SIGKILL mid-decision + orphan sweep ------------

def test_sigkill_mid_decision_replays_and_sweeps_orphans(tmp_path):
    """The acceptance drill's crash half, at the exact worst seam: the
    scheduler commits an EVICT intent to its journal and is SIGKILLed
    before delivering it (SCHED_DRILL_DIE_AT).  The victim's gang is
    now an orphan still appending to its store.  Rerunning the SAME
    command replays the journal idempotently: the dangling intent is
    resolved, the orphaned rank group is swept (its pid was journaled
    at spawn), and the queue finishes — with the victim's progress
    tape still gap- and duplicate-free."""
    py = sys.executable
    wd = str(tmp_path / "sched")
    prog = str(tmp_path / "progress")
    victim = _victim_script(tmp_path, iters=12, sleep=0.2)
    queue = tmp_path / "q.json"
    queue.write_text(json.dumps({"jobs": [
        {"job": "victim", "argv": [py, victim], "kind": "bench",
         "env": {"PROG": prog}},
        {"job": "serve", "argv": [py, "-c", "pass"], "kind": "serve",
         "ranks": 2, "after_file": prog},
    ]}))
    args = [py, os.path.join(REPO, "tools", "schedule.py"),
            "--queue", str(queue), "--workdir", wd, "--devices", "2",
            "--tick_s", "0.05"]
    env = dict(os.environ, SCHED_DRILL_DIE_AT="sched_intent:evict")
    r1 = subprocess.run(args, env=env, capture_output=True, text=True,
                        cwd=REPO, timeout=120)
    assert r1.returncode == -9, r1.stderr[-800:]
    assert "dying after sched_intent:evict:victim" in r1.stderr
    # the victim gang is orphaned and still running
    env.pop("SCHED_DRILL_DIE_AT")
    r2 = subprocess.run(args, env=env, capture_output=True, text=True,
                        cwd=REPO, timeout=120)
    assert r2.returncode == 0, r2.stderr[-800:]
    rows = [json.loads(l) for l in open(os.path.join(wd, "RUNS.jsonl"))
            if l.strip()]
    events = [r["event"] for r in rows
              if str(r.get("event", "")).startswith("sched_")]
    assert "sched_orphan_killed" in events, events
    assert "sched_intent_dropped" in events     # the dangling evict
    assert events.count("sched_queue_done") == 1
    done = {r["job"] for r in rows if r.get("event") == "sched_done"}
    assert done == {"victim", "serve"}
    lines = open(prog).read().split()
    assert lines == [f"i{i}" for i in range(12)]
    # the replay restored placement provenance: the relaunch is attempt
    # 2 and RESUMING (agree_first) — not a fresh attempt-1 placement
    # clobbering the dead incarnation's stdout dir
    places = [r for r in rows if r.get("event") == "sched_place"
              and r.get("job") == "victim"]
    assert [p["attempt"] for p in places] == [1, 2]
    assert places[0]["resumed"] is False and places[1]["resumed"] is True


def test_unsatisfiable_after_file_gate_fails_instead_of_spinning(
        tmp_path):
    """A job gated on a file nothing left in the queue can produce must
    FAIL with a why, not tick the scheduler forever (the gate's
    producer crashed out before creating it)."""
    py = sys.executable
    jobs = [
        Job(job="producer", argv=[py, "-c", "import sys; sys.exit(9)"],
            kind="train", retries=0, fleet_retries=0),
        Job(job="gated", argv=[py, "-c", "pass"], kind="serve",
            after_file=str(tmp_path / "never_created")),
    ]
    summary = _sched(tmp_path, jobs).run()
    assert summary["jobs"] == {"producer": "failed", "gated": "failed"}
    fail = _sched_rows(tmp_path, job="gated", event="sched_fail")
    assert fail and "can no longer be satisfied" in fail[0]["why"]


# ---- the serve job kind runs a REAL serving worker (PR 15) ---------------

def test_serve_job_kind_runs_serve_lm_evictions_are_loss_free(tmp_path):
    """The `serve` job kind finally launches a real workload: a
    tools/serve_lm.py worker (snapshot promoted through the validity
    path, continuous-batched decode, closed-loop driven).  The drill
    exercises BOTH eviction directions on a 1-device mesh:

    1. serve (priority 0) arrives mid-bench and evicts the bench job —
       the PR 14 SLO-preemption path, now with a real serving workload
       behind it;
    2. an urgent priority--1 job arrives mid-SERVE and evicts the
       SERVING WORKER: TERM → drain in-flight requests to completion →
       exit 143 (clean, rcs {"0": 143}) — the trainer's loss-free
       preemption protocol with "state saved" read as "every admitted
       request answered".  The relaunch re-issues exactly the
       unfinished request ids from the results tape, so the final tape
       holds every id exactly once: zero lost requests, zero repeats.
    """
    py = sys.executable
    prog = str(tmp_path / "progress")
    res = str(tmp_path / "serve_results.jsonl")
    stats = str(tmp_path / "serve_stats.json")
    victim = _victim_script(tmp_path)
    n_req = 12
    serve_argv = [py, os.path.join(REPO, "tools", "serve_lm.py"),
                  "--snapshot", str(tmp_path / "snaps"),
                  "--size", "lm_tiny", "--init_if_missing",
                  "--slots", "2", "--max_len", "32",
                  "--drive", str(n_req), "--clients", "2",
                  "--drive_max_new", "4", "--drive_think_ms", "600",
                  "--results", res, "--stats", stats]
    jobs = [
        Job(job="bench1", argv=[py, victim], kind="bench",
            env={"PROG": prog}),
        # ready the moment bench1 proves mid-run progress; the 1-device
        # mesh is busy, so admission must evict bench1.
        Job(job="serve1", argv=serve_argv, kind="serve",
            after_file=prog, retries=2, wall_timeout_s=300.0,
            kill_grace_s=15.0),
        # ready the moment serve1 completes its first request (the
        # results tape exists); outranks even `serve`, so admission
        # must evict the SERVING worker — the teardown under test.
        Job(job="urgent1", argv=[py, "-c", "pass"], kind="train",
            priority=-1, after_file=res),
    ]
    summary = _sched(tmp_path, jobs, devices=1).run()
    assert summary["jobs"] == {"bench1": "done", "serve1": "done",
                               "urgent1": "done"}
    # bench evicted for serve, serve evicted for urgent — both clean
    evict_b = _sched_rows(tmp_path, job="bench1", event="sched_evict")
    assert evict_b and evict_b[0]["for_job"] == "serve1"
    evict_s = _sched_rows(tmp_path, job="serve1", event="sched_evict")
    assert len(evict_s) == 1 and evict_s[0]["for_job"] == "urgent1"
    assert evict_s[0]["clean"] is True
    assert evict_s[0]["rcs"] == {"0": 143}      # TERM -> drain -> 143
    # the serving worker resumed: two placements, the second resuming
    places = _sched_rows(tmp_path, job="serve1", event="sched_place")
    assert [p["attempt"] for p in places] == [1, 2]
    assert places[1]["resumed"] is True
    # loss-free: every driven request id exactly once across both
    # placements — drained in-flight requests completed (never lost),
    # completed ids never re-issued (never repeated)
    ids = sorted(json.loads(line)["id"] for line in open(res))
    assert ids == list(range(n_req))
    # the bench victim's own tape stayed exact through ITS eviction
    assert open(prog).read().split() == [f"i{i}" for i in range(10)]
    # and the worker's runs are ledgered: run_start/run_end rows from
    # serve_lm itself (the fleet exports OBS_LEDGER to its ranks)
    serve_runs = [r for r in _ledger_rows(tmp_path)
                  if r.get("event") == "run_start"
                  and r.get("entrypoint") == "serve_lm"]
    assert len(serve_runs) == 2                 # one per placement
    final_stats = json.load(open(stats))
    assert final_stats["preempted"] is False    # the resume finished
    assert final_stats["size"] == "lm_tiny"


# ---- the host_loss fault + fleet seam ------------------------------------

def test_host_loss_grammar_and_named_plan():
    plan = FaultPlan.parse("host_loss@3:5.0%1", 10, 0)
    assert plan.specs == [FaultSpec("host_loss", 3, 5.0, rank=1)]
    assert plan.specs[0] in plan.loop_specs     # a boundary fault
    named = FaultPlan.parse("host_loss", 10, 0)
    assert named.specs[0].kind == "host_loss"
    assert named.specs[0].rank == 1 and named.specs[0].arg == 2.0
    assert named.for_rank(0).specs == []        # pinned to rank 1


def test_host_loss_refused_without_seam(monkeypatch):
    """A host_loss with no tombstone seam would SIGKILL the process and
    report a drill that drilled nothing — refused loudly instead."""
    monkeypatch.delenv("FLEET_HOST_DOWN_FILE", raising=False)
    hook = FaultInjectionHook(FaultPlan.parse("host_loss@1", 4, 0))
    with pytest.raises(ValueError, match="FLEET_HOST_DOWN_FILE"):
        hook.after_step(1, None, {})


def test_host_down_tombstone_expiry(tmp_path):
    """mark_host_down + FleetSupervisor.host_down: fresh = down,
    expired self-heals (the tombstone is removed), torn = still down,
    down_s=0 = down until removed."""
    fleet = FleetSupervisor(2, workdir=str(tmp_path / "fleet"))
    path = fleet._host_down_path(1)
    assert fleet.host_down(1) is False          # no tombstone
    mark_host_down(path, down_s=30.0, rank=1)
    assert fleet.host_down(1) is True
    mark_host_down(path, down_s=0.05, rank=1)
    time.sleep(0.08)
    assert fleet.host_down(1) is False          # expired + self-removed
    assert not os.path.exists(path)
    mark_host_down(path, down_s=0.0, rank=1)    # down forever
    time.sleep(0.05)
    assert fleet.host_down(1) is True
    with open(path, "w") as f:
        f.write('{"ts": 1')                     # torn mid-write
    assert fleet.host_down(1) is True


# ---- queue-completion record rides the ratchet ---------------------------

def test_bench_ratchet_recognizes_sched_queue_family(tmp_path):
    """tools/schedule.py --record writes the bench-record dialect, and
    bench_ratchet's trajectory builder folds the SCHED_queue family in
    next to the BENCH_* families."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import bench_ratchet
        import schedule as schedule_cli
    finally:
        sys.path.pop(0)
    summary = {"status": "ok", "counts": {"done": 8},
               "makespan_s": 120.0, "evictions": 1, "shrinks": 1,
               "grows": 1, "retries": 1, "jobs": {"a": "done"}}
    rec_path = tmp_path / "SCHED_queue_cpu_r14.json"
    schedule_cli.write_record(str(rec_path), summary, devices=4)
    recs = bench_ratchet.load_records([str(rec_path)])
    assert {r["metric"] for r in recs} == {"sched_queue_jobs_done",
                                           "sched_queue_jobs_per_min"}
    assert all(bench_ratchet._platform(r) == "cpu" for r in recs)
    rows = bench_ratchet.build_trajectory(str(tmp_path))
    fam = [r for r in rows if r["family"] == "SCHED_queue_cpu"]
    assert len(fam) == 1 and fam[0]["round"] == 14
    assert fam[0]["metrics"]["sched_queue_jobs_done"] == 8
    assert fam[0]["metrics"]["sched_queue_jobs_per_min"] == 4.0


# ---- fleet-level request_stop (the eviction primitive) -------------------

def test_fleet_request_stop_returns_evicted_without_restart(tmp_path):
    """The eviction primitive under the scheduler: request_stop tears
    the gang down through TERM (rcs 143) and run() returns 'evicted'
    WITHOUT a restart — distinct from the platform-preemption path,
    which restarts immediately."""
    import threading
    child = _script(tmp_path, "stopchild.py", """
        import signal, sys, time
        signal.signal(signal.SIGTERM, lambda s, f: sys.exit(143))
        time.sleep(60)
        sys.exit(0)
    """)
    fleet = FleetSupervisor(
        2, policy=RetryPolicy(retries=2, backoff_base_s=0.01),
        journal=Journal(str(tmp_path / "fleet.jsonl")),
        kill_grace_s=2.0, poll_s=0.02, seed=0,
        workdir=str(tmp_path / "fleet"))
    box = []
    t = threading.Thread(target=lambda: box.append(
        fleet.run([sys.executable, child], name="stoppable")))
    t.start()
    time.sleep(0.5)                 # both ranks up and sleeping
    fleet.request_stop("evicted")
    t.join(timeout=30)
    assert not t.is_alive() and box
    res = box[0]
    assert res.status == "evicted" and res.gang_attempts == 1
    assert res.last_rcs == {0: 143, 1: 143}
    events = Journal(str(tmp_path / "fleet.jsonl")).events()
    tear = next(e for e in events if e["event"] == "gang_teardown")
    assert tear["why"] == "evicted"
    # a stop landing between attempts: no gang is launched at all
    fleet2 = FleetSupervisor(1, workdir=str(tmp_path / "f2"), seed=0)
    fleet2.request_stop("evicted")
    res2 = fleet2.run([sys.executable, "-c", "pass"], name="never")
    assert res2.status == "evicted" and res2.last_rcs == {}


def test_sched_events_schema_is_closed():
    """The KEEP-IN-SYNC pair's content contract: every event the
    scheduler writes through _applied/_observe is in SCHED_EVENTS (plus
    the replay-only intent_dropped), and obs_query's why renderer
    covers exactly the declared set."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import obs_query
    finally:
        sys.path.pop(0)
    assert set(obs_query._WHY_RENDER) == set(SCHED_EVENTS)
