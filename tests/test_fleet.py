"""Gang supervision (resilience/fleet.py): per-rank fault targeting,
the resume-step agreement over torn/divergent rank manifests, and the
fleet state machine driven by real OS processes.

Inline on purpose: the gang children here are stdlib-only scripts
(milliseconds each, no jax import), so the whole file's verdicts land
inside the tier-1 budget.  The jax-heavy end-to-end drill (2-rank
mnist_cnn, rank-targeted kill, bitwise resume parity) lives in
tests/test_fleet_drill.py, which runs as an isolated subprocess
(tests/isolation_list.py).
"""

import json
import os
import stat
import sys
import time
import zlib

import pytest

from distributedtensorflowexample_tpu.obs import recorder as obs_recorder
from distributedtensorflowexample_tpu.obs import trace as obs_trace
from distributedtensorflowexample_tpu.resilience.faults import FaultPlan
from distributedtensorflowexample_tpu.resilience.fleet import (
    FleetSupervisor, RankLossRefused, RankLossStructurallyIllegal)
from distributedtensorflowexample_tpu.resilience.snapshot import (
    SnapshotStore, newest_common_step, valid_steps)
from distributedtensorflowexample_tpu.resilience.supervisor import (
    Journal, RetryPolicy, Supervisor)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.fleet


# ---- per-rank fault targeting (resilience/faults.py) --------------------

def test_fault_rank_grammar():
    """kind[@step][:arg][%rank]: 'kill rank 1 at step 37' is one token."""
    p = FaultPlan.parse("kill@37%1,wedge@3:2.5%0,preemption@5", 50, 0)
    by_kind = {s.kind: s for s in p.specs}
    assert (by_kind["kill"].step, by_kind["kill"].rank) == (37, 1)
    assert (by_kind["wedge"].step, by_kind["wedge"].arg,
            by_kind["wedge"].rank) == (3, 2.5, 0)
    assert by_kind["preemption"].rank is None      # untargeted: every rank


def test_fault_rank_targeting_is_deterministic_and_shares_anchor():
    """Every rank parses the SAME text+seed, so an unpinned rank-targeted
    fault lands on ONE fleet-wide anchor step — and re-parsing
    reproduces it exactly (the seed-reproducible drill contract)."""
    a = FaultPlan.parse("kill%1", 10, 7)
    b = FaultPlan.parse("kill%1", 10, 7)
    assert a.specs == b.specs
    assert 1 <= a.specs[0].step < 10
    # a different seed explores a different schedule, same grammar
    c = FaultPlan.parse("kill%1", 10, 8)
    assert c.specs[0].kind == "kill" and c.specs[0].rank == 1
    # rank filtering: rank 1 keeps the kill, rank 0 sees no faults;
    # untargeted specs survive on every rank
    assert [s.kind for s in a.for_rank(1).specs] == ["kill"]
    assert a.for_rank(0).specs == []
    d = FaultPlan.parse("kill@4%1,preemption@2", 10, 0)
    assert [s.kind for s in d.for_rank(0).specs] == ["preemption"]
    assert [s.kind for s in d.for_rank(1).specs] == ["preemption", "kill"]


# ---- resume-step agreement (resilience/snapshot.py) ---------------------

def _write_snap(directory, step, payload=b"snapshot-payload-bytes",
                torn=False):
    """A committed snapshot the manifest surface accepts, without a
    TrainState: the agreement reads manifests + payload bytes only."""
    os.makedirs(directory, exist_ok=True)
    pp = os.path.join(directory, f"snap_{step:08d}.npz")
    with open(pp, "wb") as f:
        f.write(payload)
    man = {"version": 1, "step": step, "nbytes": len(payload),
           "crc32": zlib.crc32(payload), "leaves": 1, "cursor": None,
           "meta": None}
    with open(os.path.join(directory, f"snap_{step:08d}.json"), "w") as f:
        json.dump(man, f)
    if torn:
        with open(pp, "r+b") as f:
            f.truncate(len(payload) // 2)


def test_newest_common_step_picks_max_common_valid(tmp_path):
    """Divergent newest (one rank ran ahead) and torn newest (killed
    mid-write) both fall away; the agreement is the newest step EVERY
    rank can prove."""
    r0, r1 = str(tmp_path / "r0"), str(tmp_path / "r1")
    for s in (3, 4, 5):
        _write_snap(r0, s)                 # rank 0 ran ahead to 5
    for s in (3, 4):
        _write_snap(r1, s)
    _write_snap(r1, 5, torn=True)          # rank 1's 5 tore mid-write
    assert valid_steps(r0) == [3, 4, 5]
    assert valid_steps(r1) == [3, 4]       # the torn 5 is invisible
    assert newest_common_step([r0, r1]) == 4


def test_newest_common_step_empty_and_disjoint(tmp_path):
    r0, r1 = str(tmp_path / "r0"), str(tmp_path / "r1")
    _write_snap(r0, 2)
    assert newest_common_step([r0, r1]) is None    # r1 has nothing
    _write_snap(r1, 3)
    assert newest_common_step([r0, r1]) is None    # nothing in common


def test_discard_newer_drops_divergent_timeline(tmp_path):
    d = str(tmp_path / "r0")
    for s in (2, 3, 4, 5):
        _write_snap(d, s)
    store = SnapshotStore(d)
    assert store.discard_newer(3) == [4, 5]
    assert valid_steps(d) == [2, 3]
    # no leftover manifests either: a stale manifest would make save()
    # dedupe the replayed step away
    assert not [f for f in os.listdir(d) if "00000004" in f]
    assert store.discard_newer(0) == [2, 3]        # 0 = discard all


# ---- the gang state machine (stdlib children, real processes) -----------

def _child(tmp_path, body: str) -> list[str]:
    path = tmp_path / "child.py"
    path.write_text(body)
    return [sys.executable, str(path)]


def _fleet(tmp_path, **kw):
    kw.setdefault("policy", RetryPolicy(retries=2, backoff_base_s=0.01,
                                        backoff_max_s=0.02))
    kw.setdefault("journal", Journal(str(tmp_path / "fleet.jsonl")))
    kw.setdefault("kill_grace_s", 1.0)
    kw.setdefault("poll_s", 0.05)
    kw.setdefault("seed", 0)
    kw.setdefault("workdir", str(tmp_path / "fleet"))
    return FleetSupervisor(2, **kw)


def _journal_events(tmp_path) -> list[dict]:
    with open(tmp_path / "fleet.jsonl") as f:
        return [json.loads(line) for line in f]


def test_gang_ok_and_cluster_env_surface(tmp_path, monkeypatch):
    """Every rank gets the trainers' documented env surface: TF_CONFIG
    (task index = rank), OBS_RANK, FLEET_NUM_RANKS, SUPERVISE_ATTEMPT —
    and {rank} substitution fans one argv out to per-rank args.  A
    stale FLEET_RESUME_STEP leaking in from the FLEET's own environment
    is scrubbed: only an agreement pass this fleet ran may export one."""
    monkeypatch.setenv("FLEET_RESUME_STEP", "99")   # stale outer export
    argv = _child(tmp_path, """
import json, os, sys
cfg = json.loads(os.environ["TF_CONFIG"])
print(json.dumps({"rank": os.environ["OBS_RANK"], "tag": sys.argv[1],
                  "idx": cfg["task"]["index"],
                  "workers": len(cfg["cluster"]["worker"]),
                  "n": os.environ["FLEET_NUM_RANKS"],
                  "attempt": os.environ["SUPERVISE_ATTEMPT"],
                  "resume": os.environ.get("FLEET_RESUME_STEP"),
                  "hb": os.path.basename(os.environ["SUPERVISE_HEARTBEAT"])}))
""") + ["tag{rank}"]
    fleet = _fleet(tmp_path)
    res = fleet.run(argv, name="envs", stdout_dir=str(tmp_path / "out"))
    assert res.status == "ok" and res.gang_attempts == 1
    assert res.restarts == 0 and res.last_rcs == {0: 0, 1: 0}
    for r in (0, 1):
        rec = json.loads(
            (tmp_path / "out" / f"rank{r}_attempt0.out").read_text())
        assert rec == {"rank": str(r), "tag": f"tag{r}", "idx": r,
                       "workers": 2, "n": "2", "attempt": "0",
                       "resume": None, "hb": f"hb_rank{r}"}


def test_rank_crash_tears_down_whole_gang_then_restarts(tmp_path):
    """One rank's crash is a GANG event: the healthy rank (mid-'step',
    would run 60 s) is torn down immediately, and the relaunch carries
    the next SUPERVISE_ATTEMPT."""
    argv = _child(tmp_path, """
import os, sys, time
r, a = int(os.environ["OBS_RANK"]), int(os.environ["SUPERVISE_ATTEMPT"])
if a == 0 and r == 1:
    sys.exit(7)
if a == 0:
    time.sleep(60)     # must be torn down, never waited out
sys.exit(0)
""")
    fleet = _fleet(tmp_path)
    t0 = time.monotonic()
    res = fleet.run(argv, name="crash")
    assert res.status == "ok" and res.gang_attempts == 2
    assert res.restarts == 1
    assert time.monotonic() - t0 < 30, "teardown must not wait the 60s"
    events = [e["event"] for e in _journal_events(tmp_path)]
    assert "gang_teardown" in events
    tear = next(e for e in _journal_events(tmp_path)
                if e["event"] == "gang_teardown")
    assert tear["why"] == "rank_crash" and tear["rank"] == 1


def test_gang_crash_budget_exhausts(tmp_path):
    argv = _child(tmp_path, "raise SystemExit(1)")
    fleet = _fleet(tmp_path, policy=RetryPolicy(retries=1,
                                                backoff_base_s=0.01,
                                                backoff_max_s=0.02))
    res = fleet.run(argv, name="dead")
    assert res.status == "exhausted" and res.gang_attempts == 2


def test_unanimous_preemption_exempt_from_budget(tmp_path):
    """The 143 consensus path: every rank preempted-with-save restarts
    the gang without touching the crash budget — 3 preemptions complete
    under retries=0."""
    argv = _child(tmp_path, """
import os, sys
sys.exit(143 if int(os.environ["SUPERVISE_ATTEMPT"]) < 3 else 0)
""")
    fleet = _fleet(tmp_path, policy=RetryPolicy(retries=0))
    res = fleet.run(argv, name="preempt_storm")
    assert res.status == "ok" and res.gang_attempts == 4
    assert res.preemptions == 3 and res.restarts == 3


def test_preemption_divergence_is_budgeted(tmp_path):
    """One rank exits 143 while the other trains on past the consensus
    grace: the gang cleanly lost a member but NOT unanimously — torn
    down and restarted through the budgeted path, not the exemption."""
    argv = _child(tmp_path, """
import os, sys, time
r, a = int(os.environ["OBS_RANK"]), int(os.environ["SUPERVISE_ATTEMPT"])
if a == 0 and r == 0:
    sys.exit(143)
if a == 0:
    time.sleep(60)
sys.exit(0)
""")
    fleet = _fleet(tmp_path, preempt_grace_s=0.3)
    t0 = time.monotonic()
    res = fleet.run(argv, name="diverge")
    assert res.status == "ok" and res.gang_attempts == 2
    assert res.preemptions == 0          # NOT the exempt path
    assert time.monotonic() - t0 < 30
    tear = next(e for e in _journal_events(tmp_path)
                if e["event"] == "gang_teardown")
    assert tear["why"] == "preempt_divergence"


def test_rank_heartbeat_loss_tears_down_gang(tmp_path):
    """'wedge rank 0's heartbeat': rank 0 beats once then blocks without
    exiting; the per-rank heartbeat watchdog reads the stale beat and
    tears the gang down (the failure a wall clock alone notices too
    late)."""
    argv = _child(tmp_path, """
import os, sys, time
r, a = int(os.environ["OBS_RANK"]), int(os.environ["SUPERVISE_ATTEMPT"])
open(os.environ["SUPERVISE_HEARTBEAT"], "a").close()    # first beat: arms
if a == 0 and r == 0:
    time.sleep(60)      # wedged: beats stop, process lives
sys.exit(0)
""")
    fleet = _fleet(tmp_path, heartbeat_timeout_s=0.7)
    t0 = time.monotonic()
    res = fleet.run(argv, name="wedge")
    assert res.status == "ok" and res.gang_attempts == 2
    assert time.monotonic() - t0 < 30
    tear = next(e for e in _journal_events(tmp_path)
                if e["event"] == "gang_teardown")
    assert tear["why"] == "rank_heartbeat" and tear["rank"] == 0


def test_rank_lost_taxonomy(tmp_path):
    """A host that cannot even exec degrades LOUDLY: worker-tiled state
    makes the shrink structurally illegal; replicated state refuses
    without --elastic; --elastic continues on the survivors."""
    exe0 = tmp_path / "exe0"
    exe0.write_text("#!/bin/sh\nexit 0\n")
    exe0.chmod(exe0.stat().st_mode | stat.S_IXUSR)
    argv = [str(tmp_path / "exe{rank}")]       # exe1 does not exist

    with pytest.raises(RankLossStructurallyIllegal, match="worker-tiled"):
        _fleet(tmp_path, worker_tiled=True,
               workdir=str(tmp_path / "f1")).run(argv, name="lost")
    with pytest.raises(RankLossRefused, match="--elastic"):
        _fleet(tmp_path, workdir=str(tmp_path / "f2")).run(argv,
                                                           name="lost")
    fleet = _fleet(tmp_path, elastic=True, workdir=str(tmp_path / "f3"))
    res = fleet.run(argv, name="lost")
    assert res.status == "ok" and res.ranks == [0]
    assert any(e["event"] == "rank_lost" and e["rank"] == 1
               for e in _journal_events(tmp_path))


def test_shrink_then_grow_restores_full_width(tmp_path):
    """The grow-on-recovery satellite: rank 1's host dies (tombstone +
    SIGKILL — the host_loss shape), the next spawn fails with the
    spawn-OSError the tombstone seam injects, the elastic gang shrinks
    to rank 0 and keeps working — then the tombstone expires, the
    recovery re-probe before the next relaunch re-adds rank 1, and the
    final gang runs at FULL width with ``{num_ranks}`` templating
    restored to 2 (the value each child both receives in
    FLEET_NUM_RANKS and sees substituted into its argv)."""
    argv = _child(tmp_path, """
import json, os, sys, time
rank = int(os.environ["OBS_RANK"])
n = int(os.environ["FLEET_NUM_RANKS"])
attempt = int(os.environ["SUPERVISE_ATTEMPT"])
print(json.dumps({"rank": rank, "n": n, "attempt": attempt,
                  "tag": sys.argv[1]}), flush=True)
if attempt == 0 and rank == 1:
    with open(os.environ["FLEET_HOST_DOWN_FILE"], "w") as f:
        json.dump({"ts": time.time(), "down_s": 0.8}, f)
    os.kill(os.getpid(), 9)
if n == 1:
    time.sleep(1.0)     # outlive the tombstone so the re-probe can grow
    sys.exit(1)         # force one more budgeted restart
sys.exit(0)
""") + ["w{num_ranks}"]
    fleet = _fleet(tmp_path, elastic=True,
                   policy=RetryPolicy(retries=4, backoff_base_s=0.01,
                                      backoff_max_s=0.02))
    res = fleet.run(argv, name="grow", stdout_dir=str(tmp_path / "out"))
    assert res.status == "ok", res.reasons
    assert res.ranks == [0, 1]          # full width again
    assert fleet.lost_ranks == []
    events = _journal_events(tmp_path)
    assert any(e["event"] == "rank_lost" and e["rank"] == 1
               for e in events)
    rec = next(e for e in events if e["event"] == "rank_recovered")
    assert rec["rank"] == 1 and rec["ranks"] == [0, 1]
    # the shrunken attempt really ran at width 1, the final one at 2 —
    # and the {num_ranks} argv templating tracked both
    outs = {}
    for name in os.listdir(tmp_path / "out"):
        text = (tmp_path / "out" / name).read_text().strip()
        if not text:
            continue        # torn down before its first print
        rec = json.loads(text)
        outs[(rec["rank"], rec["attempt"])] = rec
    shrunk = [r for r in outs.values() if r["n"] == 1]
    assert shrunk and all(r["tag"] == "w1" and r["rank"] == 0
                          for r in shrunk)
    last_attempt = max(a for _, a in outs)
    for rank in (0, 1):
        final = outs[(rank, last_attempt)]
        assert final["n"] == 2 and final["tag"] == "w2"


def test_agreement_pass_exports_step_and_discards_divergence(tmp_path):
    """The restart half end-to-end: rank 0's store ran ahead (3,4,5),
    rank 1 holds (3,4) + a torn 5 — after a crash the fleet agrees on
    4, DELETES every newer snapshot on every rank, and exports
    FLEET_RESUME_STEP=4 to the relaunched children."""
    snaps = {r: str(tmp_path / f"rank{r}" / "snapshots") for r in (0, 1)}
    for s in (3, 4, 5):
        _write_snap(snaps[0], s)
    for s in (3, 4):
        _write_snap(snaps[1], s)
    _write_snap(snaps[1], 5, torn=True)
    argv = _child(tmp_path, """
import os, sys
if int(os.environ["SUPERVISE_ATTEMPT"]) == 0:
    sys.exit(1)
print(os.environ["FLEET_RESUME_STEP"])
""")
    fleet = _fleet(tmp_path)
    res = fleet.run(argv, name="agree",
                    snapshot_dir_template=str(tmp_path / "rank{rank}"
                                              / "snapshots"),
                    stdout_dir=str(tmp_path / "out"))
    assert res.status == "ok" and res.agreed_steps == [4]
    for r in (0, 1):
        out = (tmp_path / "out" / f"rank{r}_attempt1.out").read_text()
        assert out.strip() == "4"
        assert valid_steps(snaps[r]) == [3, 4]     # 5 discarded on both
    agree = next(e for e in _journal_events(tmp_path)
                 if e["event"] == "resume_agreement")
    assert agree["agreed"] == 4
    assert agree["per_rank"] == {"0": [3, 4, 5], "1": [3, 4]}
    assert agree["discarded"]["0"] == [5]


def test_interrupted_agreement_discard_replayed_idempotently(
        tmp_path, monkeypatch):
    """The ROADMAP fault-library straggler: the supervisor dies
    MID-``discard_newer`` — rank 0's divergent snapshots already swept,
    rank 1's untouched (the ``FLEET_DRILL_DIE_IN_DISCARD`` seam).  The
    write-ahead ``resume_agreement`` record lets a restarted supervisor
    replay the discard BEFORE its first launch: rank 1's
    abandoned-timeline snapshot is gone, the first gang already exports
    the agreed step (no per-rank own-newest restores), and the replay
    is idempotent — the already-swept rank loses nothing, and a third
    incarnation (completion record present) replays nothing at all."""
    snaps = {r: str(tmp_path / f"rank{r}" / "snapshots") for r in (0, 1)}
    for s in (3, 4, 5):
        _write_snap(snaps[0], s)
    for s in (3, 4, 6):
        _write_snap(snaps[1], s)
    tmpl = str(tmp_path / "rank{rank}" / "snapshots")
    monkeypatch.setenv("FLEET_DRILL_DIE_IN_DISCARD", "0")
    with pytest.raises(RuntimeError, match="mid-discard"):
        _fleet(tmp_path)._agree("agree", tmpl)
    assert valid_steps(snaps[0]) == [3, 4]      # swept before the death
    assert valid_steps(snaps[1]) == [3, 4, 6]   # divergent survivor
    monkeypatch.delenv("FLEET_DRILL_DIE_IN_DISCARD")
    # Restarted supervisor, same journal: the interrupted intent must
    # replay before any child launches.
    argv = _child(tmp_path, """
import os
print(os.environ["FLEET_RESUME_STEP"])
""")
    res = _fleet(tmp_path).run(argv, name="agree",
                               snapshot_dir_template=tmpl,
                               stdout_dir=str(tmp_path / "out"))
    assert res.status == "ok" and res.gang_attempts == 1
    for r in (0, 1):
        assert valid_steps(snaps[r]) == [3, 4]
        out = (tmp_path / "out" / f"rank{r}_attempt0.out").read_text()
        assert out.strip() == "4"               # pinned to the agreement
    done = [e for e in _journal_events(tmp_path)
            if e["event"] == "resume_discard_done"]
    assert done and done[-1].get("replayed") is True
    assert done[-1]["discarded"] == {"0": [], "1": [6]}  # idempotent half
    # Completion record present -> a third incarnation replays nothing.
    assert _fleet(tmp_path, workdir=str(tmp_path / "f2"))\
        ._replay_agreement("agree", tmpl) is None


def test_supervise_fleet_cli_exhausted_never_exits_143(tmp_path,
                                                       monkeypatch):
    """An exhausted fleet whose final attempt happened to contain a
    preempted rank must not exit 143 — that code means 'terminated
    cleanly' to an outer supervisor, which would restart the exhausted
    fleet budget-free forever.  The crashing rank's own rc wins."""
    # the CLI setdefaults OBS_DIR process-wide; pin it so the export
    # does not leak past this test into later files
    monkeypatch.setenv("OBS_DIR", str(tmp_path / "flight"))
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import supervise_fleet
    finally:
        sys.path.pop(0)
    script = tmp_path / "mixed.py"
    script.write_text("""
import os, sys
sys.exit(143 if os.environ["OBS_RANK"] == "0" else 7)
""")
    rc = supervise_fleet.main([
        "--num_ranks", "2", "--retries", "0", "--backoff_base_s", "0.01",
        "--workdir", str(tmp_path / "wd"), "--snapshots", "none",
        "--seed", "0", "--",
        sys.executable, str(script)])
    assert rc == 7


# ---- obs wiring ---------------------------------------------------------

def test_flight_filename_and_payload_carry_rank(monkeypatch, tmp_path):
    """Multi-process flights must not collide on pid alone: OBS_RANK
    puts the rank in the filename AND the payload."""
    monkeypatch.setenv("OBS_DIR", str(tmp_path))
    assert os.path.basename(obs_recorder.flight_path()) == \
        f"flight_{os.getpid()}.json"
    monkeypatch.setenv("OBS_RANK", "2")
    assert os.path.basename(obs_recorder.flight_path()) == \
        f"flight_2_{os.getpid()}.json"
    rec = obs_recorder.FlightRecorder()
    assert rec.payload("test")["rank"] == 2


def test_trace_span_context_carries_rank(monkeypatch):
    monkeypatch.delenv("OBS_RANK", raising=False)
    assert "rank" not in obs_trace.event("ctx_check", 0.0)
    monkeypatch.setenv("OBS_RANK", "3")
    assert obs_trace.event("ctx_check", 0.0)["rank"] == 3


def test_prometheus_collector_export_after_tasks(monkeypatch, tmp_path):
    """OBS_PROM_DIR (the round-7 ROADMAP leftover): a completed
    supervisor task and a fleet run both refresh textfile-collector
    exports."""
    monkeypatch.setenv("OBS_PROM_DIR", str(tmp_path / "prom"))
    sup = Supervisor(policy=RetryPolicy(retries=0), seed=0)
    res = sup.run(_child(tmp_path, "raise SystemExit(0)"), name="noop")
    assert res.status == "ok"
    text = (tmp_path / "prom" / "supervise.prom").read_text()
    assert "# TYPE supervisor_attempts_total counter" in text
    fleet = _fleet(tmp_path)
    assert fleet.run(_child(tmp_path, "raise SystemExit(0)"),
                     name="noop").status == "ok"
    text = (tmp_path / "prom" / "fleet.prom").read_text()
    assert "# TYPE fleet_gang_restarts_total counter" in text
    assert "# TYPE fleet_rank_exits_total counter" in text


def test_obs_report_renders_per_rank_timeline(tmp_path, capsys):
    """A fleet journal renders the per-rank timeline section: who died,
    what tore the gang down, which step the restart agreed on."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import obs_report
    finally:
        sys.path.pop(0)
    jp = tmp_path / "fleet.jsonl"
    rows = [
        {"ts": 1.0, "event": "gang_start", "task": "drill", "attempt": 0,
         "ranks": [0, 1], "resume_step": None},
        {"ts": 2.0, "event": "rank_exit", "task": "drill", "attempt": 0,
         "rank": 1, "rc": -9},
        {"ts": 2.1, "event": "gang_teardown", "task": "drill",
         "attempt": 0, "why": "rank_crash", "rank": 1},
        {"ts": 2.4, "event": "resume_agreement", "task": "drill",
         "agreed": 4, "per_rank": {"0": [3, 4, 5], "1": [3, 4]},
         "discarded": {"0": [5], "1": []}},
        {"ts": 3.0, "event": "gang_end", "task": "drill", "attempt": 0,
         "outcome": "crash", "why": "rank 1 rc=-9"},
    ]
    jp.write_text("".join(json.dumps(r) + "\n" for r in rows))
    assert obs_report.main(["--journal", str(jp)]) == 0
    out = capsys.readouterr().out
    assert "Per-rank timeline" in out
    assert "`resume_agreement`" in out and "agreed step 4" in out
    assert "rank_crash" in out
    # the plain journal table carries the rank column too
    assert "| rank |" in out


# ---- faultline plumbing (in-process, jax already warm) ------------------

def _faultline(capsys, *args):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import faultline
    finally:
        sys.path.pop(0)
    rc = faultline.main(list(args))
    captured = capsys.readouterr()
    out = [l for l in captured.out.splitlines() if l.strip()]
    rec = json.loads(out[-1]) if out else {}
    rec["_stderr"] = captured.err
    return rc, rec


@pytest.mark.faults
def test_faultline_rank_targeted_fault_fires_only_on_its_rank(tmp_path,
                                                              capsys):
    """'preempt rank 1 at step 2' as ONE shared plan text: rank 0 runs
    clean to the end, rank 1 is preempted at exactly step 2."""
    rc, rec = _faultline(capsys, "--plan", "preemption@2%1", "--steps",
                         "4", "--workdir", str(tmp_path / "r0"),
                         "--seed", "0", "--rank", "0")
    assert rc == 0 and rec["status"] == "ok" and rec["step"] == 4
    assert rec["rank"] == 0
    rc, rec = _faultline(capsys, "--plan", "preemption@2%1", "--steps",
                         "4", "--workdir", str(tmp_path / "r1"),
                         "--seed", "0", "--rank", "1")
    assert rc == 143 and rec["status"] == "preempted" and rec["step"] == 2
    assert rec["rank"] == 1


@pytest.mark.faults
def test_faultline_honors_fleet_resume_step(tmp_path, capsys, monkeypatch):
    """FLEET_RESUME_STEP pins the restore to the agreed step (never this
    rank's own newest), and an agreed step the store cannot prove is a
    loud refusal — the divergence fix the satellite names."""
    wd = str(tmp_path / "fl")
    rc, _ = _faultline(capsys, "--plan", "none", "--steps", "4",
                       "--workdir", wd, "--seed", "0")
    assert rc == 0                      # store now holds steps 2,3,4
    monkeypatch.setenv("FLEET_RESUME_STEP", "2")
    rc, rec = _faultline(capsys, "--plan", "none", "--steps", "4",
                         "--workdir", wd, "--seed", "0")
    assert rc == 0 and rec["start_step"] == 2      # not its newest (4)
    monkeypatch.setenv("FLEET_RESUME_STEP", "9")
    rc, rec = _faultline(capsys, "--plan", "none", "--steps", "9",
                         "--workdir", wd, "--seed", "0")
    assert rc == 1
    assert "not valid in this rank's store" in rec["_stderr"]


@pytest.mark.timeline
def test_poll_health_stale_beat_evidence_is_cadence_gated(tmp_path):
    """The stalled-heartbeat straggler evidence is gated twice: a rank
    that EXITED is never evidenced by its (necessarily) stopped beat,
    and a live rank's no-beat span only counts once it exceeds
    skew_time_ratio x that rank's OWN observed beat cadence — raw
    heartbeat age at a coarse beat cadence (production trainers beat
    every ~64 steps) is noise, not evidence.  A live rank whose beat
    then genuinely freezes IS named, with the stall in the journal."""
    from distributedtensorflowexample_tpu.obs import anomaly as obs_anomaly
    fleet = _fleet(tmp_path, health_path="", skew_lag_steps=3,
                   skew_time_ratio=4.0)
    fleet._stragglers, fleet._flagged = set(), set()
    fleet._beat_obs = {}

    def _poll(**kw):
        fleet._health_polled_t = -float("inf")
        fleet._poll_health("t", 0, [0, 1], **kw)

    for rank, last in ((0, 12), (1, 5)):       # rank 1 frozen at step 5
        h = obs_anomaly.RunHealth(rank=rank)
        for s in range(1, last + 1):
            h.observe_window(s, 1, 0.01)       # healthy 10ms steps
        h.write(fleet._health_path(rank))
        open(fleet._hb_path(rank), "w").close()
    _poll()                                    # learn mtimes
    assert fleet._stragglers == set()          # no cadence known yet
    time.sleep(0.05)
    now = time.time()
    for rank in (0, 1):                        # one beat each: cadence
        os.utime(fleet._hb_path(rank), (now, now))
    _poll()                                    # interval ~0.05 s learned
    assert fleet._stragglers == set()
    time.sleep(0.3)                            # rank 1's beat freezes
    now = time.time()
    os.utime(fleet._hb_path(0), (now, now))
    _poll(exited={1: 143})                     # exited: never evidence
    assert fleet._stragglers == set()
    _poll(exited={})                           # live + frozen: named
    assert fleet._stragglers == {1}
    events = _journal_events(tmp_path)
    strag = [e for e in events if e.get("kind") == "straggler"]
    assert [e["rank"] for e in strag] == [1]
    assert "stale" in strag[0]["why"]
    # a TRANSIENT detector firing (fired_step latched, firing already
    # decayed below threshold between 0.5 s polls) still annotates the
    # journal — the same fired-or-firing read obs_report renders
    h = obs_anomaly.read_health(fleet._health_path(1))
    h["flags"]["step_time_regression"] = {"firing": False,
                                          "fired_step": 4}
    obs_anomaly.write_health(fleet._health_path(1), h)
    _poll(exited={})
    assert any(e.get("kind") == "step_time_regression"
               and e.get("rank") == 1
               for e in _journal_events(tmp_path))
