"""Data pipelines: loaders, determinism, per-process sharding (SURVEY.md C10/C11)."""

import numpy as np

from distributedtensorflowexample_tpu.data import (
    Batcher, load_cifar10, load_mnist)
from distributedtensorflowexample_tpu.data.cifar10 import augment


def test_mnist_shapes_and_range(tmp_path):
    x, y = load_mnist(str(tmp_path), "train", synthetic_size=256, source="synthetic")
    assert x.shape == (256, 28, 28, 1)
    assert x.dtype == np.float32
    assert 0.0 <= x.min() and x.max() <= 1.0
    assert y.shape == (256,) and y.dtype == np.int32
    assert set(np.unique(y)) <= set(range(10))


def test_mnist_deterministic(tmp_path):
    x1, y1 = load_mnist(str(tmp_path), "train", synthetic_size=64, source="synthetic")
    x2, y2 = load_mnist(str(tmp_path), "train", synthetic_size=64, source="synthetic")
    np.testing.assert_array_equal(x1, x2)
    np.testing.assert_array_equal(y1, y2)


def test_mnist_train_test_differ(tmp_path):
    x1, _ = load_mnist(str(tmp_path), "train", synthetic_size=64, source="synthetic")
    x2, _ = load_mnist(str(tmp_path), "test", synthetic_size=64, source="synthetic")
    assert not np.array_equal(x1, x2)


def test_cifar_shapes(tmp_path):
    x, y = load_cifar10(str(tmp_path), "train", synthetic_size=128, source="synthetic")
    assert x.shape == (128, 32, 32, 3)
    assert y.shape == (128,)


def test_cifar_tar_layout_matches_pickle_dir(tmp_path):
    """An unextracted cifar-10-python.tar.gz (the canonical download
    artifact) loads bit-identically to the extracted pickle dir."""
    import io
    import pickle
    import tarfile

    rng = np.random.RandomState(3)
    batches = {}
    for name in [f"data_batch_{i}" for i in range(1, 6)] + ["test_batch"]:
        batches[name] = {
            b"data": rng.randint(0, 256, size=(10, 3072), dtype=np.uint8),
            b"labels": rng.randint(0, 10, size=(10,)).tolist()}

    pick_dir = tmp_path / "extracted" / "cifar-10-batches-py"
    pick_dir.mkdir(parents=True)
    tar_dir = tmp_path / "tarred"
    tar_dir.mkdir()
    with tarfile.open(tar_dir / "cifar-10-python.tar.gz", "w:gz") as tf:
        for name, d in batches.items():
            (pick_dir / name).write_bytes(pickle.dumps(d))
            blob = pickle.dumps(d)
            info = tarfile.TarInfo(f"cifar-10-batches-py/{name}")
            info.size = len(blob)
            tf.addfile(info, io.BytesIO(blob))

    for split in ("train", "test"):
        xd, yd = load_cifar10(str(tmp_path / "extracted"), split)
        xt, yt = load_cifar10(str(tar_dir), split)
        np.testing.assert_array_equal(xd, xt)
        np.testing.assert_array_equal(yd, yt)
    assert xd.shape == (10, 32, 32, 3)


def test_cifar_corrupt_tar_falls_back(tmp_path, capsys):
    """A truncated/corrupt tarball (interrupted download) must behave like
    any other absent dataset — warn and fall back, not crash training."""
    (tmp_path / "cifar-10-python.tar.gz").write_bytes(b"definitely not a tar")
    x, y = load_cifar10(str(tmp_path), "train", synthetic_size=32,
                        source="fallback")
    assert x.shape == (32, 32, 32, 3)
    # stderr, NOT stdout — bench consumers json-parse every stdout line.
    assert "ignoring unreadable" in capsys.readouterr().err


def test_cifar_augment_shapes():
    rng = np.random.RandomState(0)
    x = rng.rand(8, 32, 32, 3).astype(np.float32)
    out = augment(x, rng)
    assert out.shape == x.shape
    assert not np.array_equal(out, x)


def test_batcher_epoch_and_shapes():
    x = np.arange(100, dtype=np.float32).reshape(100, 1)
    y = np.arange(100, dtype=np.int32)
    b = Batcher(x, y, batch_size=32, seed=0)
    batch = next(b)
    assert batch["image"].shape == (32, 1)
    assert batch["label"].shape == (32,)


def test_batcher_process_sharding_disjoint_and_covering():
    """Two processes drawing the same seed must split every global batch
    disjointly — the reference's per-worker dataset sharding."""
    x = np.arange(64, dtype=np.float32).reshape(64, 1)
    y = np.arange(64, dtype=np.int32)
    b0 = Batcher(x, y, batch_size=16, seed=3, process_index=0, process_count=2)
    b1 = Batcher(x, y, batch_size=16, seed=3, process_index=1, process_count=2)
    assert b0.local_batch_size == 8
    for _ in range(4):
        s0, s1 = next(b0)["label"], next(b1)["label"]
        assert len(set(s0) & set(s1)) == 0
        assert len(set(s0) | set(s1)) == 16


# ---- host-fed uint8 path (round 4) --------------------------------------
# The host path's bottleneck is the per-step H2D copy; a quantizable
# split stays uint8 through gather + upload and dequantizes in-step.

def test_batcher_auto_quantizes_and_training_is_bitwise(tmp_path):
    import jax
    import optax

    from distributedtensorflowexample_tpu.models import build_model
    from distributedtensorflowexample_tpu.parallel.sync import make_train_step
    from distributedtensorflowexample_tpu.training.state import TrainState

    x, y = load_mnist(str(tmp_path), "train", synthetic_size=256, source="synthetic")
    model = build_model("softmax")

    def run(quantize):
        b = Batcher(x, y, 32, seed=3, quantize=quantize)
        state = TrainState.create(model, optax.sgd(0.1),
                                  np.zeros((32, 28, 28, 1), np.float32))
        step = make_train_step(dequant=b.dequant)
        for _ in range(6):
            batch = next(b)
            if quantize == "auto":
                assert batch["image"].dtype == np.uint8
            state, metrics = step(state, batch)
        return (np.asarray(jax.tree.leaves(state.params)[0]),
                float(metrics["loss"]))

    p_u, l_u = run("auto")
    p_f, l_f = run("off")
    assert l_u == l_f
    np.testing.assert_array_equal(p_u, p_f)


def test_batcher_uint8_augment_is_bitwise(tmp_path):
    """Crop/flip is pure rearrangement: augmenting the uint8 batch then
    dequantizing equals the float path exactly (same rng draw order)."""
    from distributedtensorflowexample_tpu.data.device_dataset import (
        _dequant_numpy)

    x, y = load_cifar10(str(tmp_path), "train", synthetic_size=128,
                        normalize=False, source="synthetic")
    b_u = Batcher(x, y, 16, seed=5, augment_fn=augment)
    b_f = Batcher(x, y, 16, seed=5, augment_fn=augment, quantize="off")
    assert b_u.dequant == "unit" and b_f.dequant is None
    for _ in range(4):
        bu, bf = next(b_u), next(b_f)
        assert bu["image"].dtype == np.uint8
        np.testing.assert_array_equal(_dequant_numpy(bu["image"], "unit"),
                                      bf["image"])
        np.testing.assert_array_equal(bu["label"], bf["label"])


def test_uint8_batch_without_dequant_is_a_loud_error(tmp_path):
    """The guard that motivated the design: a uint8 batch reaching a
    step built without a dequant spec must fail at trace time, never
    silently train on raw 0-255 bytes."""
    import optax
    import pytest

    from distributedtensorflowexample_tpu.models import build_model
    from distributedtensorflowexample_tpu.parallel.sync import make_train_step
    from distributedtensorflowexample_tpu.training.state import TrainState

    x, y = load_mnist(str(tmp_path), "train", synthetic_size=64, source="synthetic")
    b = Batcher(x, y, 32, seed=0)
    state = TrainState.create(build_model("softmax"), optax.sgd(0.1),
                              np.zeros((32, 28, 28, 1), np.float32))
    step = make_train_step()          # no dequant spec
    with pytest.raises(TypeError, match="dequant"):
        step(state, next(b))


def test_custom_float_augment_disables_quantization(tmp_path):
    """An arbitrary float-arithmetic augment hook must keep the split
    float32 (auto-quantization only engages under u8-safe rearrangement
    augments) — and a raw uint8 split is host-dequantized for it."""
    x, y = load_mnist(str(tmp_path), "train", synthetic_size=64, source="synthetic")
    noisy = lambda im, rng: im + rng.normal(0, 0.1, im.shape).astype(im.dtype)
    b = Batcher(x, y, 32, seed=0, augment_fn=noisy)
    assert b.dequant is None
    assert next(b)["image"].dtype == np.float32

    from distributedtensorflowexample_tpu.data.device_dataset import (
        _dequant_numpy)
    u8 = np.rint(x * 255.0).astype(np.uint8)
    b2 = Batcher(u8, y, 32, seed=0, augment_fn=noisy)
    assert b2.dequant is None
    batch = next(b2)
    assert batch["image"].dtype == np.float32
    assert batch["image"].max() <= 2.0          # unit scale, not 0-255
