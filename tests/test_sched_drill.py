"""End-to-end scheduler ACCEPTANCE drill (the ISSUE's criterion): a
mixed faultline queue on the forced CPU mesh where (a) a rank's HOST is
lost mid-queue (the host_loss fault — tombstone + SIGKILL, respawn
fails like a dead host, the elastic gang shrinks and completes), and
(b) a higher-priority serving job EVICTS a running bench job through
the TERM→143→snapshot protocol — and the victim's resumed digest and
loss tape are BITWISE-equal to an uninterrupted run (zero lost steps),
with every decision answerable afterwards from ledger rows alone
(``obs_query why``).

Each job rank is a real OS process running tools/faultline.py (a fresh
jax import per child), so this file runs as an isolated subprocess
during full-suite runs (tests/isolation_list.py) — wall-time
containment, not abort risk.
"""

import glob
import json
import os
import sys

import pytest

from distributedtensorflowexample_tpu.resilience.scheduler import (
    Job, Scheduler)
from distributedtensorflowexample_tpu.resilience.supervisor import (
    RetryPolicy)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FAULTLINE = os.path.join(REPO, "tools", "faultline.py")

pytestmark = [pytest.mark.sched, pytest.mark.faults]


def _faultline_job(base, job, plan, steps, **kw):
    jdir = os.path.join(str(base), "jobs", job)
    spec = {
        "job": job,
        "argv": [sys.executable, FAULTLINE, "--plan", plan,
                 "--steps", str(steps), "--model", "softmax",
                 "--workdir", os.path.join(jdir, "rank{rank}"),
                 "--keep", "20", "--seed", "0"],
        "snapshots": os.path.join(jdir, "rank{rank}", "snapshots"),
        "steps": steps, "est_step_time_s": 1.0,
        # generous: TERM lands mid-slow-step sleep, and the save +
        # emit must complete under suite-level CPU contention
        "kill_grace_s": 30.0,
        # explicit: a fresh jax import + compile under suite load can
        # dwarf any cost-derived deadline for these tiny step counts —
        # the deadline knob is exercised in tests/test_scheduler.py
        "wall_timeout_s": 600.0}
    spec.update(kw)
    return Job.from_dict(spec)


def _straight_run(capsys, workdir: str, steps: int) -> dict:
    """The uninterrupted reference, in-process (shares the warm jit
    cache): same model/seed/steps, no faults, no delays — boundary
    sleeps never change the math, so the digests must match bitwise."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import faultline
    finally:
        sys.path.pop(0)
    rc = faultline.main(["--plan", "none", "--steps", str(steps),
                         "--model", "softmax", "--workdir", workdir,
                         "--keep", "20", "--seed", "0"])
    out = [l for l in capsys.readouterr().out.splitlines() if l.strip()]
    assert rc == 0
    return json.loads(out[-1])


def _outs(base, job):
    """All JSON tails a job's placements left, placement order."""
    recs = []
    for path in sorted(glob.glob(os.path.join(
            str(base), "sched", "jobs", job, "out", "place*", "*.out"))):
        with open(path) as f:
            lines = [l for l in f.read().splitlines() if l.strip()]
        if lines:
            recs.append((path, json.loads(lines[-1])))
    return recs


def test_acceptance_mixed_queue_host_loss_and_slo_eviction(tmp_path,
                                                           capsys):
    steps = 12
    wd = str(tmp_path / "sched")
    ledger = os.path.join(wd, "RUNS.jsonl")
    jobs = [
        # (a) rank 1's host dies at step 3 (down "forever" — arg 0):
        # crash teardown, respawn fails on the tombstone, elastic
        # shrink, the survivor resumes from the agreement and finishes.
        _faultline_job(tmp_path, "ktrain", "host_loss@3%1", steps,
                       ranks=2, kind="train", elastic=True,
                       fleet_retries=4),
        # (b) the victim: slow_rank paces it (~0.4 s/step) so the
        # serving job's arrival finds it mid-run; snapshots land every
        # step, so the eviction is loss-free by construction.
        _faultline_job(tmp_path, "bench1", "slow_rank@1:0.4", steps,
                       kind="bench"),
        # priority 0, needs the whole mesh, ready the moment bench1's
        # step-3 snapshot commits (no wall-clock guessing).
        _faultline_job(tmp_path, "serve1", "none", 4, ranks=2,
                       kind="serve",
                       after_file=os.path.join(
                           str(tmp_path), "jobs", "bench1", "rank0",
                           "snapshots", "snap_00000003.npz")),
        _faultline_job(tmp_path, "t1", "none", 4, kind="train"),
    ]
    sched = Scheduler(
        jobs, devices=2, workdir=wd, tick_s=0.1, poll_s=0.05, seed=0,
        retry_policy=RetryPolicy(retries=10**6, backoff_base_s=0.1,
                                 backoff_max_s=0.5))
    summary = sched.run()
    assert summary["jobs"] == {"ktrain": "done", "bench1": "done",
                               "serve1": "done", "t1": "done"}, summary
    assert summary["status"] == "ok"
    assert summary["evictions"] >= 1 and summary["shrinks"] >= 1

    rows = [json.loads(l) for l in open(ledger) if l.strip()]
    sched_rows = [r for r in rows
                  if str(r.get("event", "")).startswith("sched_")]

    # (a) the host loss shrank ktrain's gang — and it still finished
    shrink = [r for r in sched_rows if r["event"] == "sched_shrink"
              and r["job"] == "ktrain"]
    assert shrink and shrink[0]["lost"] == [1]
    k_outs = [rec for _, rec in _outs(tmp_path, "ktrain")]
    finals = [r for r in k_outs if r["status"] == "ok"
              and r["step"] == steps]
    assert finals, k_outs
    straight = _straight_run(capsys, str(tmp_path / "straight"), steps)
    # the surviving rank's timeline is bitwise the straight run's
    assert all(r["digest"] == straight["digest"] for r in finals)

    # (b) bench1 was evicted for serve1, TERM→143 with a snapshot...
    evict = [r for r in sched_rows if r["event"] == "sched_evict"
             and r["job"] == "bench1"]
    assert len(evict) == 1
    assert evict[0]["for_job"] == "serve1" and evict[0]["clean"] is True
    # ...and the resumed run is BITWISE the uninterrupted run: final
    # digest equal, and the concatenated loss tape equal — zero lost
    # steps, zero recomputed steps.
    b_outs = _outs(tmp_path, "bench1")
    assert len(b_outs) >= 2, b_outs
    preempted = b_outs[0][1]
    final = b_outs[-1][1]
    assert preempted["status"] == "preempted"
    assert final["status"] == "ok" and final["step"] == steps
    assert final["start_step"] == preempted["step"]     # resumed THERE
    assert final["digest"] == straight["digest"]
    tape = preempted["losses"] + final["losses"]
    assert tape == straight["losses"]

    # obs_query answers "why was bench1 preempted" from the ledger alone
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import obs_query
    finally:
        sys.path.pop(0)
    rc = obs_query.main(["why", "bench1", "--ledger", ledger])
    out = capsys.readouterr().out
    assert rc == 0
    assert "EVICTED" in out and "`serve1`" in out
    assert "preempted 1x (for `serve1`)" in out
    assert "finally completed" in out
    rc = obs_query.main(["why", "ktrain", "--ledger", ledger])
    out = capsys.readouterr().out
    assert rc == 0 and "SHRINK" in out and "host down" in out
