"""The run_training-heavy test files that execute in isolated
subprocesses (tests/test_isolated.py) during a full-suite run.

Why: XLA:CPU's in-process collective rendezvous can DEADLOCK under host
CPU contention (a participant thread that never arrives — reproduced in
round 3: 25-min hang inside one collective with terminate=1800 s, then
SIGABRT; the same failure the round-2 judge hit twice).  An abort kills
the whole pytest process, so the only robust containment is process
isolation: each of these files runs in its own pytest subprocess, and an
ABORT (not an ordinary test failure) is retried.  These are the files
with the highest collective-dispatch counts — full training loops over
the 8-virtual-device mesh.
"""

ISOLATED_FILES = [
    "test_async.py",
    "test_bench.py",        # bench_profile end-to-end = full ResNet pipeline
    "test_checkpoint.py",
    "test_dequant.py",      # bitwise parity runs = fused training loops
    "test_determinism.py",
    "test_device_data.py",
    "test_engine.py",       # per-mode bitwise Engine-vs-raw-wiring
                            # parity: full fused training tapes over the
                            # 8-device mesh in every replication mode

    "test_fleet_drill.py",  # N-rank gang drills: each rank a fresh jax
                            # subprocess — isolated for wall time, not
                            # collective-abort risk (the fast stdlib-child
                            # fleet tests stay inline in test_fleet.py)
    "test_heal_drill.py",   # self-healing acceptance drills: faultline
                            # children under remediation — isolated for
                            # wall time; the guardrail/watcher/canary
                            # tests stay inline in test_remediate.py
    "test_sched_drill.py",  # scheduler acceptance drill: faultline jobs
                            # (fresh jax per rank) under the control
                            # plane — isolated for wall time; the
                            # stdlib-child scheduler tests stay inline
                            # in test_scheduler.py
    "test_sync_dp.py",
    "test_trainers.py",
]

# Note: tests/test_bench_e2e.py (real bench.main() end-to-end) is
# deliberately NOT here — it is opt-in-only (DISTTF_BENCH_E2E=1): even
# at minimal sizes its rendezvous-bound execution costs ~20 min, too
# heavy for the default suite.  See its module docstring.
#
# tests/test_obs.py and tests/test_resilience.py / test_faultline.py are
# deliberately inline too (single device, no collectives): conftest runs
# inline files BEFORE these isolated wrappers, so their verdicts land
# inside the tier-1 870-s budget even when the wrappers' compiles don't.
