"""obs/ — unified telemetry (ISSUE 4 tentpole): registry snapshot/delta
and label semantics, span nesting with supervisor-context propagation,
flight-recorder dumps (bitwise-stable canonical JSON; on-SIGTERM via a
real subprocess), exporter golden files, the microbench guards the
tentpole promises (< 2 us per counter increment; metric-hook overhead
< 1% of the CPU bench step), the round-6 fault-library satellites
(disk-full snapshot save, heartbeat_flap, journal_torn), and the
ACCEPTANCE end-to-end: a supervised mnist_cnn run with an injected
preemption leaves flight dumps whose step counter, retry count, and
last span match the supervisor journal and the snapshot manifest, and
tools/obs_report.py renders the lot without error.

Deliberately INLINE (not in tests/isolation_list.py): single-device,
no collectives — these verdicts must land ahead of the isolated
wrappers inside the tier-1 budget.
"""

import glob
import json
import os
import signal
import subprocess
import sys
import textwrap
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributedtensorflowexample_tpu.data.synthetic import make_synthetic
from distributedtensorflowexample_tpu.models import build_model
from distributedtensorflowexample_tpu.obs import anomaly as obs_anomaly
from distributedtensorflowexample_tpu.obs import export as obs_export
from distributedtensorflowexample_tpu.obs import metrics as obs_metrics
from distributedtensorflowexample_tpu.obs import recorder as obs_recorder
from distributedtensorflowexample_tpu.obs import timeline as obs_timeline
from distributedtensorflowexample_tpu.obs import trace as obs_trace
from distributedtensorflowexample_tpu.parallel.sync import make_train_step
from distributedtensorflowexample_tpu.resilience import (
    FaultInjectionHook, FaultPlan, SnapshotHook, SnapshotStore, Supervisor,
    tear_journal)
from distributedtensorflowexample_tpu.resilience.supervisor import (
    Journal, RetryPolicy)
from distributedtensorflowexample_tpu.training.hooks import (AnomalyHook,
                                                             MetricsHook)
from distributedtensorflowexample_tpu.training.loop import TrainLoop
from distributedtensorflowexample_tpu.training.state import TrainState

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.obs


def _fresh_state(model_name: str = "softmax", batch: int = 8, seed: int = 0):
    return TrainState.create(build_model(model_name),
                             optax.sgd(0.1, momentum=0.9),
                             jnp.zeros((batch, 28, 28, 1), jnp.float32),
                             seed=seed)


def _batches(n: int, batch: int = 8):
    x, y = make_synthetic(batch * n, (28, 28, 1), 10, seed=3)
    return [{"image": jnp.asarray(x[i * batch:(i + 1) * batch]),
             "label": jnp.asarray(y[i * batch:(i + 1) * batch])}
            for i in range(n)]


@pytest.fixture(scope="module")
def sgd_step():
    return make_train_step()


@pytest.fixture()
def sink():
    events = []
    obs_trace.add_sink(events.append)
    yield events
    obs_trace.remove_sink(events.append)


# --- registry --------------------------------------------------------------

def test_registry_snapshot_delta_and_kinds():
    reg = obs_metrics.MetricsRegistry()
    c = reg.counter("steps_total", "steps")
    assert reg.counter("steps_total") is c          # idempotent
    c.inc()
    c.inc(4)
    g = reg.gauge("step")
    g.set(40)
    h = reg.histogram("win_s", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(7.0)
    s1 = reg.snapshot()
    assert s1["counters"]["steps_total"] == 5
    assert s1["gauges"]["step"]["value"] == 40
    assert s1["gauges"]["step"]["monotonic_ts"] is not None
    assert s1["histograms"]["win_s"]["count"] == 3
    assert s1["histograms"]["win_s"]["buckets"] == {
        "0.1": 1, "1.0": 2, "+Inf": 3}          # cumulative
    assert s1["histograms"]["win_s"]["sum"] == pytest.approx(7.55)
    c.inc(7)
    g.set(41)
    s2 = reg.snapshot()
    d = obs_metrics.MetricsRegistry.delta(s1, s2)
    assert d["counters"] == {"steps_total": 7}      # only what moved
    assert d["gauges"]["step"] == 41
    assert d["span_s"] >= 0
    # delta from nothing: counters count from zero, no span
    d0 = obs_metrics.MetricsRegistry.delta(None, s1)
    assert d0["counters"]["steps_total"] == 5 and d0["span_s"] is None
    # a name can't change kind
    with pytest.raises(ValueError, match="already registered"):
        reg.gauge("steps_total")


def test_registry_label_semantics():
    reg = obs_metrics.MetricsRegistry()
    fam = reg.counter("kills_total")
    a = fam.labels(why="wall", task="bench")
    assert fam.labels(task="bench", why="wall") is a    # order-canonical
    b = fam.labels(why="heartbeat", task="bench")
    assert b is not a
    a.inc(2)
    b.inc()
    snap = reg.snapshot()["counters"]
    assert snap['kills_total{task="bench",why="wall"}'] == 2
    assert snap['kills_total{task="bench",why="heartbeat"}'] == 1
    # the untouched bare series is elided from a labeled-only family
    assert "kills_total" not in snap
    fam.inc()                                           # now it's real
    assert reg.snapshot()["counters"]["kills_total"] == 1


def test_counter_increment_microbench_guard():
    """Tentpole promise: the lock-free hot path stays under 2 us per
    increment on CPU (best-of-repeats to shrug off host load)."""
    c = obs_metrics.MetricsRegistry().counter("bench_total")
    n, best = 20000, float("inf")
    for _ in range(5):
        t0 = time.perf_counter()
        for _ in range(n):
            c.inc()
        best = min(best, (time.perf_counter() - t0) / n)
    assert c.value == 5 * n
    assert best < 2e-6, f"counter inc {best * 1e9:.0f}ns >= 2us"


# --- trace spans -----------------------------------------------------------

def test_span_nesting_and_env_context(sink, monkeypatch):
    monkeypatch.setenv("SUPERVISE_ATTEMPT", "3")
    monkeypatch.setenv("OBS_PHASE", "full_bench")
    with obs_trace.span("outer", step=7):
        with obs_trace.span("inner"):
            pass
        obs_trace.event("synth", 0.25, n=4)
    inner, synth, outer = sink[-3:]
    assert (inner["name"], inner["parent"], inner["depth"]) == (
        "inner", "outer", 1)
    assert (synth["name"], synth["parent"], synth["depth"]) == (
        "synth", "outer", 1)
    assert synth["dur_s"] == 0.25 and synth["n"] == 4
    assert (outer["parent"], outer["depth"], outer["step"]) == (None, 0, 7)
    for ev in (inner, synth, outer):
        assert ev["attempt"] == 3 and ev["phase"] == "full_bench"
    assert outer["dur_s"] >= inner["dur_s"] >= 0
    # spans feed the registry histogram too
    snap = obs_metrics.registry().snapshot()["histograms"]
    assert snap['span_seconds{name="outer"}']["count"] >= 1


def test_span_attrs_writable_and_exception_safe(sink):
    with pytest.raises(RuntimeError):
        with obs_trace.span("doomed") as attrs:
            attrs["rc"] = 7
            raise RuntimeError("boom")
    assert sink[-1]["name"] == "doomed" and sink[-1]["rc"] == 7
    assert obs_trace._stack() == []         # stack unwound


def test_trace_jsonl_file_sink(tmp_path, monkeypatch):
    path = str(tmp_path / "trace.jsonl")
    monkeypatch.setenv("OBS_TRACE_FILE", path)
    with obs_trace.span("a"):
        pass
    with obs_trace.span("b", step=2):
        pass
    # a caller-forgotten foreign scalar serializes via str, and even a
    # truly unserializable attr must not raise out of span.__exit__ —
    # telemetry must never kill the run it observes
    with obs_trace.span("c", arr=np.float32(1.5)):
        pass
    recs = [json.loads(l) for l in open(path)]
    assert [r["name"] for r in recs] == ["a", "b", "c"]
    assert recs[1]["step"] == 2
    assert recs[2]["arr"] == "1.5"


def test_atomic_write_unlinks_tmp_on_failed_write(tmp_path, monkeypatch):
    """The disk-full-survival path retries every interval; a leaked
    partial tmp per failed attempt would eat the last free bytes."""

    def _enospc(fd):
        raise OSError(28, "No space left on device")

    monkeypatch.setattr(obs_recorder.os, "fsync", _enospc)
    with pytest.raises(OSError):
        obs_recorder.atomic_write(str(tmp_path / "f.json"), b"data")
    monkeypatch.undo()
    assert os.listdir(str(tmp_path)) == []


# --- flight recorder -------------------------------------------------------

def test_flight_dump_bitwise_stable_and_canonical(tmp_path, monkeypatch):
    """Two dumps of an unchanged recorder are bitwise identical, and the
    file is canonical JSON (re-serializing the parsed content reproduces
    the exact bytes) — what makes flights diffable across attempts."""
    monkeypatch.setattr(obs_metrics, "_now", lambda: 123.456789)
    reg = obs_metrics.MetricsRegistry()
    reg.counter("train_steps_total").inc(6)
    reg.gauge("train_step").set(6)
    rec = obs_recorder.FlightRecorder(registry=reg)
    rec.note(model="softmax")
    rec.record_span({"name": "snapshot", "dur_s": 0.004, "step": 6})
    rec.record_loss(6, 1.25)
    rec.record_delta({"counters": {"train_steps_total": 6}})
    p1 = rec.dump("sigterm", path=str(tmp_path / "f1.json"))
    p2 = rec.dump("sigterm", path=str(tmp_path / "f2.json"))
    raw1, raw2 = open(p1, "rb").read(), open(p2, "rb").read()
    assert raw1 == raw2
    flight = json.loads(raw1)
    assert raw1 == (json.dumps(flight, sort_keys=True, indent=1)
                    + "\n").encode()
    assert flight["reason"] == "sigterm"
    assert flight["notes"] == {"model": "softmax"}
    assert flight["loss_tail"] == [[6, 1.25]]
    assert flight["metrics"]["counters"]["train_steps_total"] == 6
    assert flight["spans"][-1]["name"] == "snapshot"


def test_flight_rings_are_bounded():
    rec = obs_recorder.FlightRecorder(max_spans=4, max_loss=3,
                                      registry=obs_metrics.MetricsRegistry())
    for i in range(10):
        rec.record_span({"name": f"s{i}"})
        rec.record_loss(i, float(i))
    payload = rec.payload("exit")
    assert [s["name"] for s in payload["spans"]] == ["s6", "s7", "s8", "s9"]
    assert payload["loss_tail"] == [[7, 7.0], [8, 8.0], [9, 9.0]]


def test_flight_dump_on_sigterm_subprocess(tmp_path):
    """install(sigterm=True) in a process with no handler of its own:
    SIGTERM leaves a flight file with the recorded evidence, then the
    process still dies BY the signal (honest wait-status).  Stdlib-only
    — no jax import in the child, so this is cheap."""
    script = textwrap.dedent("""
        import os, signal, sys
        sys.path.insert(0, %r)
        from distributedtensorflowexample_tpu.obs import (
            metrics, recorder, trace)
        rec = recorder.install(sigterm=True)
        rec.note(drill="sigterm")
        metrics.counter("child_steps_total").inc(5)
        with trace.span("phase_a", step=7):
            pass
        os.kill(os.getpid(), signal.SIGTERM)
    """) % REPO
    env = {**os.environ, "OBS_DIR": str(tmp_path),
           "SUPERVISE_ATTEMPT": "1", "OBS_PHASE": "drill"}
    env.pop("OBS_TRACE_FILE", None)
    proc = subprocess.run([sys.executable, "-c", script], env=env,
                          capture_output=True, timeout=60)
    assert proc.returncode == -signal.SIGTERM
    dumps = [n for n in os.listdir(str(tmp_path))
             if n.startswith("flight_") and n.endswith(".json")]
    assert len(dumps) == 1
    flight = json.loads(open(os.path.join(str(tmp_path), dumps[0])).read())
    assert flight["reason"] == "sigterm"
    assert flight["attempt"] == 1 and flight["phase"] == "drill"
    assert flight["notes"] == {"drill": "sigterm"}
    assert flight["metrics"]["counters"]["child_steps_total"] == 5
    assert flight["spans"][-1]["name"] == "phase_a"
    assert flight["spans"][-1]["step"] == 7


# --- exporters -------------------------------------------------------------

def test_prometheus_exporter_golden(tmp_path):
    reg = obs_metrics.MetricsRegistry()
    reg.counter("train_steps_total", "completed global steps").inc(12)
    reg.counter("supervisor_kills_total").labels(why="wall").inc()
    reg.gauge("train_step").set(12)
    h = reg.histogram("snap_s", buckets=(0.3, 1.0))
    h.observe(0.25)                 # binary-exact values: the golden
    h.observe(0.5)                  # pins bytes, so no repr drift
    golden = (
        "# TYPE snap_s histogram\n"
        'snap_s_bucket{le="0.3"} 1\n'
        'snap_s_bucket{le="1.0"} 2\n'
        'snap_s_bucket{le="+Inf"} 2\n'
        "snap_s_sum 0.75\n"
        "snap_s_count 2\n"
        "# TYPE supervisor_kills_total counter\n"
        'supervisor_kills_total{why="wall"} 1\n'
        "# TYPE train_step gauge\n"
        "train_step 12\n"
        "# HELP train_steps_total completed global steps\n"
        "# TYPE train_steps_total counter\n"
        "train_steps_total 12\n")
    assert obs_export.prometheus_text(reg) == golden
    path = obs_export.write_prometheus_textfile(
        str(tmp_path / "obs.prom"), reg)
    assert open(path).read() == golden


def test_jsonl_exporter_snapshots_and_deltas(tmp_path):
    reg = obs_metrics.MetricsRegistry()
    c = reg.counter("steps_total")
    exp = obs_export.JsonlExporter(str(tmp_path / "obs.jsonl"))
    c.inc(3)
    exp.export(reg)
    c.inc(2)
    exp.export(reg)
    lines = [json.loads(l) for l in open(str(tmp_path / "obs.jsonl"))]
    assert lines[0]["delta"] is None
    assert lines[0]["snapshot"]["counters"]["steps_total"] == 3
    assert lines[1]["snapshot"]["counters"]["steps_total"] == 5
    assert lines[1]["delta"]["counters"] == {"steps_total": 2}


# --- MetricsHook + overhead guard ------------------------------------------

class _FakeLoop:
    start_step = 0


def test_metrics_hook_feeds_registry_and_recorder(sink):
    reg = obs_metrics.registry()
    before = reg.snapshot()["counters"].get("train_steps_total", 0)
    hook = MetricsHook(every=2)
    hook.begin(_FakeLoop())
    rec = obs_recorder.FlightRecorder(registry=reg)
    # stand in for the installed recorder without installing one
    installed = obs_recorder._GLOBAL
    obs_recorder._GLOBAL = rec
    try:
        for step in range(1, 5):
            hook.after_step(step, None, {"loss": np.float32(step * 0.5)})
    finally:
        obs_recorder._GLOBAL = installed
    snap = reg.snapshot()
    assert snap["counters"]["train_steps_total"] - before == 4
    assert snap["gauges"]["train_step"]["value"] == 4
    assert snap["gauges"]["train_loss"]["value"] == 2.0
    # loss sampled on the every=2 marks only; ring has both marks
    assert list(rec._loss) == [[2, 1.0], [4, 2.0]]
    steps_events = [e for e in sink if e["name"] == "steps"]
    assert [e["step"] for e in steps_events] == [2, 4]
    assert all(e["n"] == 2 for e in steps_events)
    # the delta ring got one entry (second mark vs first)
    assert len(rec._deltas) == 1
    assert rec._deltas[0]["counters"]["train_steps_total"] == 2


def test_metrics_hook_overhead_under_1pct_of_bench_step(sgd_step):
    """ACCEPTANCE guard: per-boundary hook cost vs the measured CPU
    bench step (mnist_cnn — the headline workload) in the SAME process
    under the SAME load.  every=100 is the bench-like cadence (loss
    fetch + registry snapshot amortized across boundaries)."""
    state = _fresh_state("mnist_cnn")
    batch = _batches(1)[0]
    state, metrics = sgd_step(state, batch)      # compile
    jax.block_until_ready(metrics)
    times = []
    for _ in range(5):
        t0 = time.perf_counter()
        state, metrics = sgd_step(state, batch)
        jax.block_until_ready(metrics)
        times.append(time.perf_counter() - t0)
    step_s = min(times)
    # The FULL round-10 production stack at boundary cadence:
    # MetricsHook + AnomalyHook (trainers/common.py installs both) —
    # the <1% budget covers the anomaly detectors' hot-path half too.
    hook = MetricsHook(every=100)
    anom = AnomalyHook(every=100)
    hook.begin(_FakeLoop())
    anom.begin(_FakeLoop())
    fetched = {"loss": np.asarray(metrics["loss"])}
    n = 1000
    t0 = time.perf_counter()
    for i in range(1, n + 1):
        hook.after_step(i, state, fetched)
        anom.after_step(i, state, fetched)
    hook_s = (time.perf_counter() - t0) / n
    assert hook_s < 0.01 * step_s, (
        f"metric+anomaly hooks {hook_s * 1e6:.2f}us/boundary >= 1% of "
        f"the {step_s * 1e3:.1f}ms CPU bench step")


# --- satellite: disk-full snapshot save ------------------------------------

def test_snapshot_hook_survives_disk_full(tmp_path, sgd_step, monkeypatch,
                                          capsys):
    """ROADMAP round-6 by name: a full disk mid-run logs + increments
    snapshot_save_failures instead of killing the run; the newest VALID
    snapshot on disk is untouched and restores."""
    store = SnapshotStore(str(tmp_path / "snaps"))
    state = _fresh_state()
    hook = SnapshotHook(store, every=1, cursor={"seed": 0})
    batches = _batches(3)
    hook.begin(_FakeLoop())
    state, m = sgd_step(state, batches[0])
    hook.after_step(1, state, m)                 # healthy save at step 1
    assert store.latest_valid() == 1

    def _enospc(self, path, data):
        raise OSError(28, "No space left on device", path)

    fails = obs_metrics.registry().counter("snapshot_save_failures")
    before = fails.value
    monkeypatch.setattr(SnapshotStore, "_atomic_write", _enospc)
    for i, b in enumerate(batches[1:], start=2):
        state, m = sgd_step(state, b)
        hook.after_step(i, state, m)             # fails, must not raise
    hook.end(state)                              # final retry also fails
    assert fails.value - before == 3             # steps 2, 3 + end
    err = capsys.readouterr().err
    assert "No space left" in err and "continuing" in err
    monkeypatch.undo()
    assert store.latest_valid() == 1             # prior snapshot intact
    restored = store.restore(_fresh_state(seed=9))
    assert int(restored.step) == 1


# --- satellite: new fault kinds --------------------------------------------

def test_new_fault_kinds_parse_deterministically():
    for text in ("heartbeat_flap", "journal_torn",
                 "heartbeat_flap,journal_torn"):
        a = FaultPlan.parse(text, 10, seed=4)
        b = FaultPlan.parse(text, 10, seed=4)
        assert ([(s.kind, s.step, s.arg) for s in a.specs]
                == [(s.kind, s.step, s.arg) for s in b.specs])
        assert all(1 <= s.step < 10 for s in a.specs)
    # a different seed explores a different schedule
    steps4 = {s.step for s in FaultPlan.parse("heartbeat_flap", 1000, 4).specs}
    steps5 = {s.step for s in FaultPlan.parse("heartbeat_flap", 1000, 5).specs}
    assert steps4 != steps5
    # classification: flap rides the loop, torn journal is post-exit
    plan = FaultPlan.parse("journal_torn,heartbeat_flap@2:0.01", 8, 0)
    assert [s.kind for s in plan.post_exit_specs] == ["journal_torn"]
    assert sorted(s.kind for s in plan.loop_specs) == [
        "heartbeat_flap", "preemption"]


def test_heartbeat_flap_beats_at_the_timeout_edge(tmp_path, sgd_step,
                                                  monkeypatch):
    """The flap blocks for exactly the supervisor-exported timeout, then
    touches the heartbeat — the supervisor's strictly-greater staleness
    check must see a beat ON the edge as alive."""
    hb = str(tmp_path / "hb")
    monkeypatch.setenv("SUPERVISE_HEARTBEAT", hb)
    monkeypatch.setenv("SUPERVISE_HEARTBEAT_TIMEOUT_S", "0.3")
    from distributedtensorflowexample_tpu.resilience.faults import (
        FLAP_EDGE_MARGIN_S)
    plan = FaultPlan.parse("heartbeat_flap@2", 3, 0)
    state = _fresh_state()
    t0 = time.perf_counter()
    loop = TrainLoop(sgd_step, iter(_batches(3)), 3,
                     hooks=[FaultInjectionHook(plan)])
    loop.run(state)
    # blocked to the edge (minus the deterministic-survivability margin)
    assert time.perf_counter() - t0 >= 0.3 - FLAP_EDGE_MARGIN_S
    assert os.path.exists(hb)                    # then beat
    assert time.time() - os.path.getmtime(hb) < 0.3
    injected = obs_metrics.registry().snapshot()["counters"]
    assert injected['faults_injected_total{kind="heartbeat_flap"}'] >= 1


def test_heartbeat_flap_refuses_with_no_edge(sgd_step, monkeypatch):
    """nan_loss-on-uint8 discipline: a flap with no timeout to aim at
    (no arg, no supervisor env) refuses loudly instead of reporting a
    drill that exercised nothing."""
    monkeypatch.delenv("SUPERVISE_HEARTBEAT_TIMEOUT_S", raising=False)
    plan = FaultPlan.parse("heartbeat_flap@1", 2, 0)
    loop = TrainLoop(sgd_step, iter(_batches(2)), 2,
                     hooks=[FaultInjectionHook(plan)])
    with pytest.raises(ValueError, match="no timeout edge"):
        loop.run(_fresh_state())


def test_journal_torn_replay_skips_tail(tmp_path):
    path = str(tmp_path / "journal.jsonl")
    j = Journal(path)
    j.write("attempt_start", task="a", attempt=0)
    j.write("task_done", task="a")
    j.write("task_done", task="b")
    assert tear_journal(path)
    data = open(path, "rb").read()
    assert not data.endswith(b"\n")              # genuinely torn mid-line
    state = Journal(path).replay()
    assert state["done"] == {"a"}                # intact lines survive
    assert not state["wedged"]
    # empty/missing files refuse to tear
    assert not tear_journal(str(tmp_path / "missing"))
    open(str(tmp_path / "empty"), "w").close()
    assert not tear_journal(str(tmp_path / "empty"))


def test_journal_write_heals_a_torn_tail(tmp_path):
    """An append landing AFTER a tear must not merge with the torn
    fragment into one unparseable line (which would eat a LIVE record,
    not just the dead fragment): write() heals the tail with a newline
    first, so replay loses at most the fragment."""
    path = str(tmp_path / "journal.jsonl")
    j = Journal(path)
    j.write("task_done", task="a")
    j.write("attempt_start", task="b", attempt=0)
    assert tear_journal(path)
    j.write("task_done", task="b")               # post-tear append
    state = Journal(path).replay()
    assert state["done"] == {"a", "b"}           # the live record survived
    parseable = 0
    for line in open(path).read().splitlines():
        try:
            json.loads(line)
            parseable += 1
        except ValueError:
            pass                                 # the healed-off fragment
    assert parseable == 2


def test_flight_dump_with_nan_loss_is_strict_json(tmp_path):
    """The NaN-guard postmortem — the one dump whose point is recording
    a NaN — must still be strict JSON (no bare NaN tokens): non-finite
    floats serialize as their string names."""
    reg = obs_metrics.MetricsRegistry()
    reg.gauge("train_loss").set(float("nan"))
    rec = obs_recorder.FlightRecorder(registry=reg)
    rec.record_loss(2, float("nan"))
    rec.record_loss(3, float("inf"))
    path = rec.dump("nan_guard", path=str(tmp_path / "f.json"))
    raw = open(path).read()
    assert "NaN" not in raw and "Infinity" not in raw
    flight = json.loads(raw)                     # strict-parseable
    assert flight["loss_tail"] == [[2, "nan"], [3, "inf"]]
    assert flight["metrics"]["gauges"]["train_loss"]["value"] == "nan"


def test_faultline_journal_torn_plumbing(tmp_path, capsys, monkeypatch):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import faultline
    sys.path.pop(0)
    path = str(tmp_path / "sup.jsonl")
    Journal(path).write("attempt_start", task="drill", attempt=0)
    intact = open(path, "rb").read()
    monkeypatch.setenv("SUPERVISE_JOURNAL", path)
    rc = faultline.main(["--plan", "journal_torn", "--steps", "4",
                         "--workdir", str(tmp_path / "wd"), "--seed", "1"])
    captured = capsys.readouterr()
    assert rc == 143                             # paired preemption saved
    assert "tore journal" in captured.err
    torn = open(path, "rb").read()
    assert len(torn) < len(intact) and intact.startswith(torn)


# --- ACCEPTANCE: supervised drill leaves a cross-checkable postmortem ------

def test_acceptance_supervised_mnist_cnn_flight_matches_journal_and_manifest(
        tmp_path):
    """Supervised mnist_cnn drill with an injected preemption: every
    attempt leaves a flight dump; the preempted attempt's step gauge and
    last span name the same step the snapshot manifest committed, the
    flight count and attempt ids match the journal, and obs_report
    renders flights + journal without error."""
    wd = str(tmp_path / "drill")
    flights_dir = str(tmp_path / "flight")
    os.makedirs(flights_dir)
    journal_path = str(tmp_path / "journal.jsonl")
    out = str(tmp_path / "out.json")
    sup = Supervisor(policy=RetryPolicy(retries=2, backoff_base_s=0.01),
                     journal=Journal(journal_path), seed=0)
    res = sup.run(
        [sys.executable, os.path.join(REPO, "tools", "faultline.py"),
         "--plan", "preempt", "--steps", "4", "--model", "mnist_cnn",
         "--workdir", wd, "--seed", "0", "--keep", "8"],
        name="drill", stdout_path=out,
        env_extra={"OBS_DIR": flights_dir})
    assert res.status == "ok" and res.attempts == 2      # 143 then 0

    flights = {}
    for name in os.listdir(flights_dir):
        f = json.loads(open(os.path.join(flights_dir, name)).read())
        flights[f["attempt"]] = f
    journal = [json.loads(l) for l in open(journal_path)]
    starts = [r for r in journal if r["event"] == "attempt_start"]
    ends = [r for r in journal if r["event"] == "attempt_end"]
    # retry count: one flight per journaled attempt, ids aligned
    assert sorted(flights) == [r["attempt"] for r in starts] == [0, 1]
    assert [r["rc"] for r in ends] == [143, 0]

    final = json.loads(open(out).read().strip().splitlines()[-1])
    k = final["start_step"]                              # preemption step
    assert 1 <= k < 4
    store = SnapshotStore(os.path.join(wd, "snapshots"))

    preempted = flights[0]
    assert preempted["reason"] == "preempted"
    assert preempted["phase"] == "drill"                 # OBS_PHASE export
    # step counter matches the snapshot manifest the preemption committed
    assert preempted["metrics"]["gauges"]["train_step"]["value"] == k
    assert preempted["metrics"]["counters"]["train_steps_total"] == k
    assert store.manifest(k)["cursor"]["step"] == k
    # last span: the fault marker that caused the 143 the journal
    # recorded, at the same step the snapshot span just committed
    assert preempted["spans"][-1]["name"] == "fault"
    assert preempted["spans"][-1]["kind"] == "preemption"
    assert preempted["spans"][-1]["step"] == k
    snap_spans = [s for s in preempted["spans"] if s["name"] == "snapshot"]
    assert snap_spans[-1]["step"] == k
    assert preempted["loss_tail"][-1][0] == k

    finished = flights[1]
    assert finished["attempt"] == 1
    assert finished["metrics"]["gauges"]["train_step"]["value"] == 4
    assert finished["metrics"]["counters"]["train_steps_total"] == 4 - k
    assert store.latest_valid() == 4                     # manifest agrees
    assert finished["spans"][-1]["name"] == "snapshot"
    assert finished["spans"][-1]["step"] == 4

    # obs_report renders flights + journal without error
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "obs_report.py"),
         "--dir", flights_dir, "--journal", journal_path],
        capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0, proc.stderr
    assert "# Telemetry report" in proc.stdout
    assert "`train_steps_total`" in proc.stdout
    assert "`snapshot`" in proc.stdout
    assert "attempt_end" in proc.stdout
    assert "preempted" in proc.stdout


def test_obs_report_cli_help_runs():
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "obs_report.py"),
         "--help"], capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0 and "--journal" in proc.stdout


# === round 10: timeline merge + online anomaly detection ====================

timeline_mark = pytest.mark.timeline


@timeline_mark
def test_ewma_regression_pins_baseline_and_latches():
    """The boiled-frog defense: the baseline is pinned over the first
    ``warmup`` samples and NEVER updates, so a later sustained slowdown
    scores against the run's own healthy start; ``observe`` returns True
    exactly once (the latch) while ``firing`` tracks the live z."""
    det = obs_anomaly.EwmaRegression(warmup=4, alpha=1.0, z_thresh=4.0,
                                     skip_first=0)
    fired = [det.observe(0.010, step=s) for s in range(1, 5)]
    assert fired == [False] * 4 and det.armed
    mu0, sigma0 = det.mu0, det.sigma0
    assert mu0 == pytest.approx(0.010)
    assert not det.observe(0.010, step=5) and det.z == pytest.approx(0.0)
    assert det.observe(0.050, step=6)            # first crossing fires
    assert det.fired_step == 6 and det.firing
    assert not det.observe(0.060, step=7)        # latched: never re-fires
    assert det.firing and det.fired_step == 6
    assert (det.mu0, det.sigma0) == (mu0, sigma0)  # baseline still pinned
    payload = det.payload()
    assert payload["fired_step"] == 6 and payload["firing"]
    assert payload["baseline_mean_s"] == pytest.approx(0.010)


@timeline_mark
def test_ewma_sigma_floor_and_skip_first():
    """Near-constant warmup samples must not turn scheduler jitter into
    a flag (sigma floored at min_sigma_frac * mean), and the compile-
    dominated first boundary is skipped without feeding the baseline."""
    det = obs_anomaly.EwmaRegression(warmup=3, z_thresh=8.0, skip_first=1,
                                     min_sigma_frac=0.05)
    assert not det.observe(9.0, step=1)          # compile window: skipped
    assert det.n == 0 and det.ewma is None
    for s in (2, 3, 4):
        det.observe(0.020, step=s)
    assert det.sigma0 == pytest.approx(0.05 * 0.020)   # floored, not 0
    det.observe(0.021, step=5)                   # 5% jitter: z ~ 1, quiet
    assert not det.firing


@timeline_mark
def test_detect_skew_laggard_vs_straggler():
    """Lag alone names a laggard, never a straggler: the straggler
    verdict needs slowness evidence (own regression flag, or step time
    over time_ratio x the OTHER ranks' median — self-excluded so a
    2-rank fleet's straggler cannot mask itself)."""
    # fewer than two reporters: skew is a relation, nothing to say
    assert obs_anomaly.detect_skew({0: {"step": 8}})["stragglers"] == []
    # lagging but no evidence (still compiling / unlucky sample)
    out = obs_anomaly.detect_skew(
        {0: {"step": 10, "step_time_s": 0.01},
         1: {"step": 6, "step_time_s": None}}, lag_steps=3)
    assert out["laggards"] == [1] and out["stragglers"] == []
    assert "no slowness evidence" in out["why"][1]
    # lagging with its own regression firing
    out = obs_anomaly.detect_skew(
        {0: {"step": 10, "step_time_s": 0.01},
         1: {"step": 6, "step_time_s": 0.3, "regression_firing": True}},
        lag_steps=3)
    assert out["stragglers"] == [1] and out["max_step"] == 10
    assert out["lag_steps"] == {0: 0, 1: 4}
    assert "regression firing" in out["why"][1]
    # lagging + slow vs the other ranks' median (no flag of its own)
    out = obs_anomaly.detect_skew(
        {0: {"step": 10, "step_time_s": 0.01},
         1: {"step": 5, "step_time_s": 0.25}}, lag_steps=3,
        time_ratio=4.0)
    assert out["stragglers"] == [1]
    assert "fleet median" in out["why"][1]
    # lagging + stale heartbeat (wedged-but-alive: its health report
    # predates the stall so step_time_s looks healthy, but the beat —
    # touched every boundary — has gone stale)
    out = obs_anomaly.detect_skew(
        {0: {"step": 10, "step_time_s": 0.01, "hb_age_s": 0.01},
         1: {"step": 5, "step_time_s": 0.01, "hb_age_s": 7.0}},
        lag_steps=3, time_ratio=4.0)
    assert out["stragglers"] == [1]
    assert "heartbeat" in out["why"][1] and "stale" in out["why"][1]
    # under the lag threshold nothing is even a laggard
    out = obs_anomaly.detect_skew(
        {0: {"step": 10, "step_time_s": 0.01},
         1: {"step": 9, "step_time_s": 9.9, "regression_firing": True}},
        lag_steps=3)
    assert out["laggards"] == [] and out["stragglers"] == []


@timeline_mark
def test_plateau_nan_sentinels_and_spread_fraction():
    det = obs_anomaly.PlateauSentinel(window=3, min_delta=1e-3)
    for s, loss in enumerate((1.0, 0.9, 0.8, 0.7), start=1):
        assert not det.observe(loss, step=s)     # still improving
    assert not det.observe(0.7, step=5)
    assert not det.observe(0.7, step=6)          # 0.8 still pre-window best
    assert det.observe(0.7, step=7)              # window best == best_before
    assert det.fired_step == 7
    assert not det.observe(0.7, step=8)          # still firing: edge only
    # NaN is the other sentinel's job and must not poison the window
    assert not det.observe(float("nan"), step=7)
    # improve -> the window re-arms -> a SECOND plateau fires again
    for s, loss in enumerate((0.5, 0.4, 0.3, 0.3), start=9):
        assert not det.observe(loss, step=s)
    assert not det.firing
    assert det.observe(0.3, step=13) or det.observe(0.3, step=14)
    assert det.firing and det.fired_step == 7    # first plateau pinned

    rh = obs_anomaly.RunHealth(rank=3)
    assert rh.observe_loss(4, float("nan")) == ["nan_loss"]
    assert rh.observe_loss(5, float("nan")) == []        # latched
    assert rh.flags["nan_loss"] == {"firing": True, "fired_step": 4}

    assert obs_anomaly.spread_fraction([100.0, 80.0]) == pytest.approx(0.2)
    assert obs_anomaly.spread_fraction([50.0]) == 0.0
    assert obs_anomaly.spread_fraction([]) == 0.0
    # tolerant-reader contract: a malformed record (string repeats,
    # None) must not crash the ratchet's verdict protocol
    assert obs_anomaly.spread_fraction(["1.2", None, 100.0, 80.0]) == \
        pytest.approx(0.2)


@timeline_mark
def test_health_json_roundtrip_and_tolerant_read(tmp_path):
    rh = obs_anomaly.RunHealth(rank=1)
    rh.observe_window(5, 1, 0.01)
    path = str(tmp_path / "health.json")
    rh.write(path)
    payload = obs_anomaly.read_health(path)
    assert payload["kind"] == "rank" and payload["rank"] == 1
    assert payload["step"] == 5 and payload["version"] == 1
    assert set(payload["flags"]) == {"step_time_regression", "nan_loss",
                                     "loss_plateau"}
    # tolerant by contract: missing and torn both read as None
    assert obs_anomaly.read_health(str(tmp_path / "absent.json")) is None
    (tmp_path / "torn.json").write_text('{"version": 1, "ste')
    assert obs_anomaly.read_health(str(tmp_path / "torn.json")) is None


@timeline_mark
def test_span_events_carry_both_clocks_pinned_bitwise(sink, tmp_path,
                                                      monkeypatch):
    """The satellite clock fix: every span event carries t0_s (monotonic
    — honest durations) AND t0_unix (wall — the cross-process alignment
    axis), derived through the _now/_wall seams so a pinned-clock test
    still gets bitwise-stable flight dumps."""
    monkeypatch.setattr(obs_metrics, "_now", lambda: 100.0)
    monkeypatch.setattr(obs_metrics, "_wall", lambda: 1700000000.0)
    ev = obs_trace.event("win", 2.5)
    assert ev["t0_s"] == 97.5
    assert ev["t0_unix"] == 1699999997.5         # same instant, wall axis
    with obs_trace.span("s"):
        pass
    assert sink[-1]["t0_unix"] == 1700000000.0
    reg = obs_metrics.MetricsRegistry()
    rec = obs_recorder.FlightRecorder(registry=reg)
    rec.record_span(ev)
    p1 = rec.dump("manual", path=str(tmp_path / "f1.json"))
    p2 = rec.dump("manual", path=str(tmp_path / "f2.json"))
    raw1, raw2 = open(p1, "rb").read(), open(p2, "rb").read()
    assert raw1 == raw2                          # bitwise under pinned clock
    flight = json.loads(raw1)
    assert flight["start_unix"] == 1700000000.0
    assert flight["spans"][0]["t0_unix"] == 1699999997.5


@timeline_mark
def test_fleet_dir_sources_health_discovery_stays_in_bounds(tmp_path):
    """Health discovery covers the fleet layout (<workdir>/health*.json
    next to a <workdir>/flight dir) but must NOT glob the journal
    directory's parent: a default workdir of /tmp/fleet would otherwise
    merge some other process's /tmp/health.json into this report."""
    wd = tmp_path / "fleet"
    (wd / "flight").mkdir(parents=True)
    (wd / "health.json").write_text("{}")
    (wd / "health_rank0.json").write_text("{}")
    foreign = tmp_path / "health.json"           # parent of the workdir
    foreign.write_text("{}")
    src = obs_timeline.fleet_dir_sources(
        flight_dir=str(wd / "flight"), journal=str(wd / "fleet.jsonl"))
    assert str(wd / "health.json") in src["health_paths"]
    assert str(wd / "health_rank0.json") in src["health_paths"]
    assert str(foreign) not in src["health_paths"]
    # an arbitrary --dir (not the <workdir>/flight or <journal>_flight
    # layouts) must not widen the glob to ITS parent either
    src = obs_timeline.fleet_dir_sources(flight_dir=str(wd))
    assert str(wd / "health.json") in src["health_paths"]
    assert str(foreign) not in src["health_paths"]


def _mini_flight(rank: int, pid: int, spans: list, coll: bool = False):
    flight = {"rank": rank, "attempt": 0, "pid": pid, "spans": spans}
    if coll:
        flight["metrics"] = {"gauges": {
            'collective_ops_per_step{op="all-reduce"}': {"value": 3},
            'collective_bytes_per_step{op="all-reduce"}': {"value": 1024}}}
    return flight


@timeline_mark
def test_timeline_merge_calibration_coverage_and_chrome_trace(tmp_path):
    """The tentpole merge: wall-ordered cross-rank events, stamp-less
    events calibrated from a sibling's monotonic->wall offset, torn
    sources costed as coverage entries (never a raised report), and a
    Perfetto/Chrome-trace export with one lane per rank."""
    s0 = [{"name": "steps", "t0_s": 10.0, "t0_unix": 1000.0, "dur_s": 0.5,
           "step": 2, "n": 2, "input_s": 0.1, "compute_s": 0.3,
           "hook_s": 0.05},
          # pre-fix event: no wall stamp — the sibling above calibrates it
          {"name": "snapshot", "t0_s": 10.3, "dur_s": 0.03}]
    s1 = [{"name": "steps", "t0_s": 50.0, "t0_unix": 1000.3, "dur_s": 1.5,
           "step": 2, "n": 2, "input_s": 0.1, "compute_s": 1.3,
           "hook_s": 0.05}]
    (tmp_path / "flight_0_11.json").write_text(
        json.dumps(_mini_flight(0, 11, s0, coll=True)))
    (tmp_path / "flight_1_22.json").write_text(
        json.dumps(_mini_flight(1, 22, s1)))
    (tmp_path / "flight_2_33.json").write_text("{torn")
    journal = tmp_path / "fleet.jsonl"
    journal.write_text(json.dumps(
        {"event": "gang_start", "ts": 999.9, "ranks": [0, 1, 2]}) + "\n"
        + '{"event": "torn_li')
    # An OBS_TRACE_FILE from rank 0's process: the same span closes
    # land in the flight ring AND here (trace events carry rank from
    # OBS_RANK but no pid) — the merge must count each close ONCE or
    # anatomy totals double.
    trace_file = tmp_path / "trace0.jsonl"
    trace_file.write_text("".join(
        json.dumps({**ev, "rank": 0, "attempt": 0}) + "\n" for ev in s0))
    merged = obs_timeline.merge(
        flight_paths=[str(tmp_path / f"flight_{r}_{p}.json")
                      for r, p in ((0, 11), (1, 22), (2, 33))],
        trace_paths=[str(trace_file)],
        journal_paths=[str(journal)])
    assert len(merged["events"]) == len(s0) + len(s1)    # deduped
    assert all(e["pid"] == 11 for e in merged["events"]
               if e["rank"] == 0)           # the flight copy was kept
    cov = merged["coverage"]
    assert cov["ranks_present"] == [0, 1]
    assert cov["ranks_missing"] == [2]           # named, not raised
    assert list(cov["unreadable"]) == [str(tmp_path / "flight_2_33.json")]
    assert cov["torn_lines"] == 1
    assert cov["uncalibrated_events"] == 0
    snap = next(e for e in merged["events"] if e["name"] == "snapshot")
    assert snap["t0_unix"] == pytest.approx(1000.3)      # offset 990.0
    stamps = [e["t0_unix"] for e in merged["events"]]
    assert stamps == sorted(stamps)              # wall-ordered
    assert merged["collectives"][0]["all-reduce"] == {"ops": 3,
                                                      "bytes": 1024}

    trace = obs_timeline.chrome_trace(merged)
    lanes = {e["args"]["name"] for e in trace["traceEvents"]
             if e["ph"] == "M" and e["name"] == "process_name"}
    # one lane per rank + the unranked fleet lane the journal marker uses
    assert lanes == {"rank 0", "rank 1", "fleet / unranked"}
    xs = [e for e in trace["traceEvents"] if e["ph"] == "X"]
    assert xs and all(e["ts"] >= 0 for e in xs)  # relative to base stamp
    marks = [e for e in trace["traceEvents"] if e["ph"] == "i"]
    assert [m["name"] for m in marks] == ["gang_start"]
    json.dumps(trace)                            # loadable = serializable

    rows = obs_timeline.step_anatomy(merged)
    by_rank = {r["rank"]: r for r in rows}
    assert by_rank[1]["window_s"] == 1.5 and by_rank[0]["window_s"] == 0.5
    assert by_rank[1]["compute_s"] > by_rank[0]["compute_s"]  # the skew
    assert by_rank[0]["snapshot_s"] == pytest.approx(0.03)
    assert by_rank[0]["hook_s"] == pytest.approx(0.02)  # snap broken out
    assert by_rank[0]["collective_ops"] == 6     # 3 ops/step x n=2
    tot = obs_timeline.anatomy_totals(rows)
    assert tot["window_s"] == pytest.approx(2.0) and tot["n"] == 4


@timeline_mark
def test_step_anatomy_ties_out_against_loop_counters(sgd_step, sink):
    """ACCEPTANCE tie-out: the per-window anatomy deltas the 'steps'
    events carry sum to the loop_*_seconds_total counters the TrainLoop
    feeds (input/compute exactly; the hook column trails one boundary by
    construction — its counter is still open when the mark reads it)."""
    reg = obs_metrics.registry()
    in_c = reg.counter("loop_input_seconds_total")
    stp_c = reg.counter("loop_step_seconds_total")
    hk_c = reg.counter("loop_hook_seconds_total")
    before = (in_c.value, stp_c.value, hk_c.value)
    state = _fresh_state()
    TrainLoop(sgd_step, iter(_batches(6)), 6,
              hooks=[MetricsHook(every=2)]).run(state)
    d_in = in_c.value - before[0]
    d_stp = stp_c.value - before[1]
    d_hk = hk_c.value - before[2]
    steps_events = [e for e in sink if e["name"] == "steps"]
    assert [e["step"] for e in steps_events] == [2, 4, 6]
    for e in steps_events:
        assert e["t0_unix"] is not None          # mergeable across ranks
        assert e["input_s"] >= 0 and e["compute_s"] > 0

    rows = obs_timeline.step_anatomy(
        {"events": steps_events, "markers": [], "health": [],
         "collectives": {}})
    assert [(r["step_from"], r["step_to"], r["n"]) for r in rows] == [
        (0, 2, 2), (2, 4, 2), (4, 6, 2)]
    tot = obs_timeline.anatomy_totals(rows)
    assert tot["input_s"] == pytest.approx(d_in, abs=1e-4)
    assert tot["compute_s"] == pytest.approx(d_stp, abs=1e-4)
    assert 0.0 <= tot["hook_s"] <= d_hk + 1e-6   # trails one boundary
    for r in rows:
        assert r["other_s"] >= 0.0               # window >= categorized sum
        assert r["window_s"] >= r["input_s"] + r["compute_s"] - 1e-6


@timeline_mark
def test_anomaly_hook_fires_counters_health_and_flight(tmp_path, sink,
                                                       monkeypatch):
    """The hook half of the tentpole: a regression firing bumps
    anomaly_flags_total, emits an 'anomaly' trace event, dumps a flight
    mid-run (the ring must cover the steps AROUND the anomaly), and the
    health.json the fleet polls carries the fired step; the NaN sentinel
    rides the train_loss gauge MetricsHook already set — no second
    device fetch."""
    monkeypatch.setenv("OBS_DIR", str(tmp_path / "flight"))
    ticks = iter([0.0]                           # begin()
                 + [0.010 * s for s in range(1, 7)]       # 6 fast windows
                 + [0.06 + 0.25 * k for k in range(1, 5)])  # then slow
    monkeypatch.setattr(time, "perf_counter", lambda: next(ticks))
    obs_metrics.gauge("train_loss").set(1.0)
    reg = obs_metrics.registry()
    flags_key = 'anomaly_flags_total{kind="step_time_regression"}'
    nan_key = 'anomaly_flags_total{kind="nan_loss"}'
    before = reg.snapshot()["counters"]
    rh = obs_anomaly.RunHealth(
        rank=0, step_time=obs_anomaly.EwmaRegression(
            warmup=4, alpha=1.0, z_thresh=4.0, skip_first=0))
    hook = AnomalyHook(every=2, health_path=str(tmp_path / "health.json"),
                       health=rh)
    installed = obs_recorder._GLOBAL
    obs_recorder._GLOBAL = obs_recorder.FlightRecorder(registry=reg)
    try:
        for step in range(1, 7):                 # healthy: warmup + quiet
            hook.after_step(step, None, None)
        snap = reg.snapshot()["counters"]
        assert snap.get(flags_key, 0) == before.get(flags_key, 0)
        hook.after_step(7, None, None)           # first slow window: fires
        obs_metrics.gauge("train_loss").set(float("nan"))
        hook.after_step(8, None, None)           # due mark: NaN sentinel
        snap = reg.snapshot()["counters"]
        assert snap.get(flags_key, 0) - before.get(flags_key, 0) == 1
        assert snap.get(nan_key, 0) - before.get(nan_key, 0) == 1
    finally:
        obs_recorder._GLOBAL = installed
    kinds = [e["kind"] for e in sink if e["name"] == "anomaly"]
    assert kinds == ["step_time_regression", "nan_loss"]
    assert rh.step_time.fired_step == 7 and rh.nan_step == 8
    flights = glob.glob(str(tmp_path / "flight" / "flight_*.json"))
    assert flights                               # dumped mid-run, pre-death
    assert json.load(open(flights[0]))["reason"].startswith("anomaly_")
    health = obs_anomaly.read_health(str(tmp_path / "health.json"))
    assert health["flags"]["step_time_regression"]["fired_step"] == 7
    assert health["flags"]["nan_loss"] == {"firing": True, "fired_step": 8}
    z = reg.snapshot()["gauges"]["anomaly_step_time_z"]["value"]
    assert z > 4.0


def _bench_ratchet():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import bench_ratchet
    finally:
        sys.path.pop(0)
    return bench_ratchet


def _write_record(path, value, metric="steps_per_sec_per_chip", **detail):
    rec = {"metric": metric, "value": value, "unit": "steps/s/chip",
           "detail": detail}
    with open(path, "a") as f:
        f.write(json.dumps(rec) + "\n")


@timeline_mark
def test_bench_ratchet_explains_variance_gates_regressions(tmp_path,
                                                           capsys):
    """The trajectory guard: a raw drop with the window-normalized
    vs_roofline held is chip variance (explained); roofline regressed or
    absent is UNEXPLAINED (exit 1); a self-noisy measurement
    (spread_frac over --noise) or a documented OUTAGE round can never
    gate."""
    rt = _bench_ratchet()
    d = str(tmp_path)
    floor = str(tmp_path / "floor.json")
    json.dump({"dots_passed_floor": 220}, open(floor, "w"))
    _write_record(os.path.join(d, "BENCH_x_r01.json"), 100.0,
                  vs_roofline=0.50, platform="chip")
    # sentinel lines are not measurements
    with open(os.path.join(d, "BENCH_x_r01.json"), "a") as f:
        f.write(json.dumps({"metric": "steps_per_sec_per_chip",
                            "unit": "unavailable"}) + "\n")
    _write_record(os.path.join(d, "BENCH_x_r02.json"), 50.0,
                  vs_roofline=0.55, platform="chip")
    common = ["--records_dir", d, "--floor_file", floor]
    assert rt.main(common + ["--json"]) == 0     # roofline held: explained
    verdict = json.loads(capsys.readouterr().out)
    assert verdict["findings"][0]["severity"] == "explained"
    assert "vs_roofline held" in verdict["findings"][0]["why"]

    _write_record(os.path.join(d, "BENCH_x_r03.json"), 40.0,
                  vs_roofline=0.20, platform="chip")
    assert rt.main(common + ["--json"]) == 1     # roofline regressed too
    verdict = json.loads(capsys.readouterr().out)
    worst = [f for f in verdict["findings"] if f["severity"] == "regression"]
    assert worst and "vs_roofline also regressed" in worst[0]["why"]

    # the same drop measured noisily cannot gate
    _write_record(os.path.join(d, "BENCH_x_r04.json"), 40.0,
                  vs_roofline=0.20, platform="chip",
                  repeats=[10.0, 40.0])          # spread 0.75 > 0.25
    assert rt.main(common + ["--json"]) == 0
    verdict = json.loads(capsys.readouterr().out)
    assert all(f["severity"] != "regression" for f in verdict["findings"])

    # a checked-in outage postmortem adjudicates its whole round
    _write_record(os.path.join(d, "BENCH_x_r05.json"), 30.0,
                  platform="chip")
    open(os.path.join(d, "OUTAGE_r05.md"), "w").write("degraded window")
    assert rt.main(common + ["--json"]) == 0
    verdict = json.loads(capsys.readouterr().out)
    assert any("documented outage" in f["why"]
               for f in verdict["findings"])


@timeline_mark
def test_bench_ratchet_floor_gates_and_ratchets_upward_only(tmp_path,
                                                            capsys):
    rt = _bench_ratchet()
    floor = str(tmp_path / "floor.json")
    json.dump({"dots_passed_floor": 220}, open(floor, "w"))
    common = ["--records_dir", str(tmp_path), "--floor_file", floor]
    assert rt.main(common + ["--dots", "220"]) == 0
    assert rt.main(common + ["--dots", "219"]) == 1      # below the floor
    out = capsys.readouterr().out
    assert "FLOOR VIOLATION" in out
    assert rt.main(common + ["--raise_floor", "219"]) == 1   # refuses down
    assert json.load(open(floor))["dots_passed_floor"] == 220
    assert rt.main(common + ["--raise_floor", "224"]) == 0
    assert json.load(open(floor))["dots_passed_floor"] == 224
    # the repo's checked-in floor file is the tool's default target
    checked_in = json.load(open(os.path.join(REPO, "tests",
                                             "tier1_floor.json")))
    assert checked_in["dots_passed_floor"] >= 220


@timeline_mark
def test_obs_report_renders_gaps_and_exports_trace(tmp_path, monkeypatch):
    """The torn-flight satellite end-to-end: a fleet dir with one good
    flight, one torn flight, and a health.json renders the ranks it HAS
    and lists the gaps — and --format trace/json export the same merge
    machine-readably."""
    flight_dir = tmp_path / "flight"
    flight_dir.mkdir()
    monkeypatch.setattr(obs_metrics, "_now", lambda: 50.0)
    monkeypatch.setattr(obs_metrics, "_wall", lambda: 1700000100.0)
    monkeypatch.setenv("OBS_RANK", "0")
    rec = obs_recorder.FlightRecorder(registry=obs_metrics.MetricsRegistry())
    rec.record_span({"name": "steps", "t0_s": 49.0, "t0_unix": 1700000099.0,
                     "dur_s": 1.0, "step": 4, "n": 2, "input_s": 0.2,
                     "compute_s": 0.7, "hook_s": 0.05})
    rec.record_loss(4, 1.5)
    rec.dump("exit", path=str(flight_dir / "flight_0_11.json"))
    (flight_dir / "flight_1_22.json").write_text('{"rank": 1, "spa')
    rh = obs_anomaly.RunHealth(rank=0)
    rh.observe_window(4, 1, 0.01)
    rh.write(str(flight_dir / "health_rank0.json"))
    monkeypatch.delenv("OBS_RANK")

    def _report(*extra):
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "obs_report.py"),
             "--dir", str(flight_dir), *extra],
            capture_output=True, text=True, timeout=60)
        assert proc.returncode == 0, proc.stderr
        return proc.stdout

    md = _report()
    assert "Merged timeline" in md
    assert "ranks present**: [0]" in md
    assert "ranks MISSING" in md and "[1]" in md          # the gap list
    assert "unreadable" in md and "flight_1_22.json" in md
    assert "Step anatomy" in md and "Health" in md
    trace = json.loads(_report("--format", "trace"))
    assert any(e.get("ph") == "X" for e in trace["traceEvents"])
    assert trace["otherData"]["coverage"]["ranks_missing"] == [1]
    merged = json.loads(_report("--format", "json"))
    assert merged["coverage"]["ranks_present"] == [0]
    assert merged["anatomy"][0]["step_to"] == 4
    assert merged["health"][0]["rank"] == 0


@timeline_mark
def test_merge_and_exports_tolerate_string_ranks(tmp_path):
    """OBS_RANK need not be numeric (trace._context and the flight
    writer both keep e.g. "chief" as-is): coverage sorts, the anatomy
    sort, and Perfetto lane assignment must survive mixed int/str ranks
    instead of raising mid-outage."""
    evs = [{"name": "steps", "t0_s": 1.0, "t0_unix": 1000.0, "dur_s": 0.5,
            "step": 2, "n": 2, "rank": 0, "input_s": 0.1,
            "compute_s": 0.3, "hook_s": 0.0},
           {"name": "steps", "t0_s": 2.0, "t0_unix": 1000.6, "dur_s": 0.5,
            "step": 2, "n": 2, "rank": "chief", "input_s": 0.1,
            "compute_s": 0.3, "hook_s": 0.0}]
    tf = tmp_path / "t.jsonl"
    tf.write_text("".join(json.dumps(e) + "\n" for e in evs))
    merged = obs_timeline.merge(trace_paths=[str(tf)])
    assert merged["coverage"]["ranks_present"] == [0, "chief"]
    rows = obs_timeline.step_anatomy(merged)
    assert [r["rank"] for r in rows] == [0, "chief"]
    trace = obs_timeline.chrome_trace(merged)
    xs = {e["pid"] for e in trace["traceEvents"] if e.get("ph") == "X"}
    assert len(xs) == 2 and 0 in xs              # distinct int lanes


@timeline_mark
def test_obs_report_health_only_invocation(tmp_path):
    """Health files alone are renderable input: a postmortem where the
    flights tore away but health.json survived must not exit 2."""
    rh = obs_anomaly.RunHealth(rank=0)
    rh.observe_window(4, 1, 0.01)
    path = tmp_path / "health_rank0.json"
    rh.write(str(path))
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "obs_report.py"),
         "--health", str(path)],
        capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0, proc.stderr
    assert "Health" in proc.stdout and "rank 0" in proc.stdout


@timeline_mark
def test_anomaly_hook_excludes_save_spans_from_step_time(monkeypatch):
    """A periodic checkpoint is seconds against sub-ms steps: without
    excluding checkpoint/snapshot/eval span time from the detector's
    window, the first post-warmup save would score as a guaranteed
    false regression against the warmup-pinned baseline.  A genuinely
    slow window (no span accounting for it) still fires."""
    clock = {"t": 0.0}
    monkeypatch.setattr(time, "perf_counter", lambda: clock["t"])
    hook = AnomalyHook(every=1)
    hook._health.step_time = obs_anomaly.EwmaRegression(
        warmup=4, z_thresh=8.0, skip_first=0)
    hook.begin(_FakeLoop())
    snap = obs_metrics.histogram("span_seconds").labels(name="snapshot")
    for s in range(1, 6):
        clock["t"] += 0.01
        hook.after_step(s, None, {})
    assert hook._health.step_time.armed
    clock["t"] += 5.01                       # 5 s of it inside the save
    snap.observe(5.0)
    hook.after_step(6, None, {})
    assert not hook._health.step_time.firing     # excluded: not a regression
    clock["t"] += 5.0                        # unexplained 5 s window
    hook.after_step(7, None, {})
    assert hook._health.step_time.firing
    assert hook._health.step_time.fired_step == 7
