"""Native C++ data-loader parity tests (SURVEY.md §2 C10/C11 rebuild).

Every native entry point is checked bit-exact against the numpy fallback
it replaces — the two paths must be indistinguishable to training.
"""

import gzip
import struct

import numpy as np
import pytest

from distributedtensorflowexample_tpu import native
from distributedtensorflowexample_tpu.data.cifar10 import _augment_numpy
# The canonical f32 1/255 multiply (data/dequant.py): the native parser
# and every numpy loader compute bytes -> floats this one way, so the
# parity references here must too (a division rounds differently on
# 126/256 byte values).
from distributedtensorflowexample_tpu.data.dequant import U8_UNIT_SCALE

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native toolchain unavailable")


def _idx_image_bytes(n=50, rows=28, cols=28, seed=0):
    rng = np.random.RandomState(seed)
    pixels = rng.randint(0, 256, size=n * rows * cols, dtype=np.uint8)
    return struct.pack(">IIII", 2051, n, rows, cols) + pixels.tobytes(), pixels


def _idx_label_bytes(n=50, seed=0):
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, 10, size=n, dtype=np.uint8)
    return struct.pack(">II", 2049, n) + labels.tobytes(), labels


def test_idx_image_parse_matches_numpy():
    raw, pixels = _idx_image_bytes()
    got = native.parse_idx_images(raw)
    want = pixels.reshape(50, 28, 28, 1).astype(np.float32) * U8_UNIT_SCALE
    np.testing.assert_array_equal(got, want)


def test_idx_label_parse_matches_numpy():
    raw, labels = _idx_label_bytes()
    np.testing.assert_array_equal(native.parse_idx_labels(raw),
                                  labels.astype(np.int32))


def test_idx_parse_rejects_garbage():
    with pytest.raises(ValueError):
        native.parse_idx_images(b"\x00" * 32)
    with pytest.raises(ValueError):
        native.parse_idx_labels(b"\x00" * 32)


def test_cifar_parse_matches_numpy():
    rng = np.random.RandomState(1)
    n = 20
    recs = rng.randint(0, 256, size=(n, 3073), dtype=np.uint8)
    recs[:, 0] = rng.randint(0, 10, size=n)
    got_imgs, got_lbls = native.parse_cifar(recs.tobytes())
    want = (recs[:, 1:].reshape(n, 3, 32, 32).transpose(0, 2, 3, 1)
            .astype(np.float32) * U8_UNIT_SCALE)
    np.testing.assert_array_equal(got_imgs, want)
    np.testing.assert_array_equal(got_lbls, recs[:, 0].astype(np.int32))


def test_gather_f32_matches_fancy_indexing():
    rng = np.random.RandomState(2)
    src = rng.randn(500, 28, 28, 1).astype(np.float32)
    idx = rng.randint(0, 500, size=128)
    np.testing.assert_array_equal(native.gather(src, idx), src[idx])


def test_gather_i32_matches_fancy_indexing():
    rng = np.random.RandomState(3)
    src = rng.randint(0, 10, size=500).astype(np.int32)
    idx = rng.randint(0, 500, size=128)
    np.testing.assert_array_equal(native.gather(src, idx), src[idx])


def test_augment_matches_numpy_fallback():
    rng = np.random.RandomState(4)
    images = rng.randn(32, 32, 32, 3).astype(np.float32)
    ys = rng.randint(0, 9, size=32).astype(np.int32)
    xs = rng.randint(0, 9, size=32).astype(np.int32)
    flips = rng.rand(32) < 0.5
    got = native.augment_crop_flip(images, ys, xs, flips)
    want = _augment_numpy(images, ys, xs, flips)
    np.testing.assert_array_equal(got, want)


def test_fused_gather_augment_matches_two_step():
    rng = np.random.RandomState(5)
    src = rng.randn(200, 32, 32, 3).astype(np.float32)
    idx = rng.randint(0, 200, size=64)
    ys = rng.randint(0, 9, size=64).astype(np.int32)
    xs = rng.randint(0, 9, size=64).astype(np.int32)
    flips = rng.rand(64) < 0.5
    got = native.gather_augment(src, idx, ys, xs, flips)
    want = _augment_numpy(src[idx], ys, xs, flips)
    np.testing.assert_array_equal(got, want)


def test_mnist_loader_uses_native_and_matches(tmp_path):
    """End-to-end: IDX files on disk parse identically through load_mnist."""
    from distributedtensorflowexample_tpu.data.mnist import load_mnist

    img_raw, pixels = _idx_image_bytes(n=40)
    lbl_raw, labels = _idx_label_bytes(n=40)
    with gzip.open(tmp_path / "train-images-idx3-ubyte.gz", "wb") as f:
        f.write(img_raw)
    with gzip.open(tmp_path / "train-labels-idx1-ubyte.gz", "wb") as f:
        f.write(lbl_raw)
    x, y = load_mnist(str(tmp_path), "train")
    np.testing.assert_array_equal(
        x, pixels.reshape(40, 28, 28, 1).astype(np.float32) * U8_UNIT_SCALE)
    np.testing.assert_array_equal(y, labels.astype(np.int32))


def test_batcher_native_gather_parity():
    """Batcher yields identical batches whether or not native is used."""
    from distributedtensorflowexample_tpu.data.pipeline import Batcher

    rng = np.random.RandomState(6)
    images = rng.randn(300, 28, 28, 1).astype(np.float32)
    labels = rng.randint(0, 10, size=300).astype(np.int32)
    b1 = Batcher(images, labels, 64, seed=9)
    b2 = Batcher(images, labels, 64, seed=9)
    import distributedtensorflowexample_tpu.native.loader as loader
    batch_native = next(b1)
    saved = loader._LIB
    loader._LIB, loader._FAILED = None, True    # force numpy fallback
    try:
        batch_numpy = next(b2)
    finally:
        loader._LIB, loader._FAILED = saved, False
    np.testing.assert_array_equal(batch_native["image"], batch_numpy["image"])
    np.testing.assert_array_equal(batch_native["label"], batch_numpy["label"])


def test_batcher_fused_augment_parity():
    """CIFAR Batcher with augmentation: the fused native gather+augment
    yields bit-identical batches to the numpy gather-then-augment path."""
    from distributedtensorflowexample_tpu.data.cifar10 import augment
    from distributedtensorflowexample_tpu.data.pipeline import Batcher

    rng = np.random.RandomState(7)
    images = rng.randn(300, 32, 32, 3).astype(np.float32)
    labels = rng.randint(0, 10, size=300).astype(np.int32)
    b1 = Batcher(images, labels, 64, seed=11, augment_fn=augment)
    b2 = Batcher(images, labels, 64, seed=11, augment_fn=augment)
    import distributedtensorflowexample_tpu.native.loader as loader
    batch_native = next(b1)
    saved = loader._LIB
    loader._LIB, loader._FAILED = None, True    # force numpy fallback
    try:
        batch_numpy = next(b2)
    finally:
        loader._LIB, loader._FAILED = saved, False
    np.testing.assert_array_equal(batch_native["image"], batch_numpy["image"])
    np.testing.assert_array_equal(batch_native["label"], batch_numpy["label"])


# ---- uint8 variants (round 4: quantized host path) ----------------------

def test_gather_u8_matches_fancy_indexing():
    rng = np.random.RandomState(3)
    src = rng.randint(0, 256, size=(50, 8, 8, 3), dtype=np.uint8)
    idx = rng.randint(0, 50, size=16).astype(np.int64)
    out = native.gather(src, idx)
    assert out.dtype == np.uint8
    np.testing.assert_array_equal(out, src[idx])


def test_augment_u8_matches_numpy_fallback():
    rng = np.random.RandomState(4)
    images = rng.randint(0, 256, size=(12, 32, 32, 3), dtype=np.uint8)
    ys = rng.randint(0, 9, size=12).astype(np.int32)
    xs = rng.randint(0, 9, size=12).astype(np.int32)
    flips = (rng.rand(12) < 0.5)
    out = native.augment_crop_flip(images, ys, xs, flips)
    assert out.dtype == np.uint8
    np.testing.assert_array_equal(out, _augment_numpy(images, ys, xs, flips))


def test_fused_gather_augment_u8_matches_two_step():
    rng = np.random.RandomState(5)
    src = rng.randint(0, 256, size=(40, 32, 32, 3), dtype=np.uint8)
    idx = rng.randint(0, 40, size=10).astype(np.int64)
    ys = rng.randint(0, 9, size=10).astype(np.int32)
    xs = rng.randint(0, 9, size=10).astype(np.int32)
    flips = (rng.rand(10) < 0.5)
    fused = native.gather_augment(src, idx, ys, xs, flips)
    assert fused.dtype == np.uint8
    np.testing.assert_array_equal(
        fused, _augment_numpy(src[idx], ys, xs, flips))


def test_uint8_augment_commutes_with_dequant():
    """The whole-path invariant the quantized pipeline rests on:
    augment(uint8) then LUT-dequant == dequant then augment."""
    from distributedtensorflowexample_tpu.data.device_dataset import (
        _dequant_numpy)
    rng = np.random.RandomState(6)
    images = rng.randint(0, 256, size=(8, 32, 32, 3), dtype=np.uint8)
    ys = rng.randint(0, 9, size=8).astype(np.int32)
    xs = rng.randint(0, 9, size=8).astype(np.int32)
    flips = (rng.rand(8) < 0.5)
    a = _dequant_numpy(native.augment_crop_flip(images, ys, xs, flips),
                       "cifar")
    b = _augment_numpy(_dequant_numpy(images, "cifar"), ys, xs, flips)
    np.testing.assert_array_equal(a, b)
