"""TensorBoard event-file writer (utils/tfevents.py) — framing, protobuf
encoding, CRC verification, and the MetricsLogger integration.

The reference wrote ``tf.summary`` scalars; these tests pin the rebuild's
tfevents output to the on-disk format TensorBoard actually reads (TFRecord
framing with masked CRC32C, Event/Summary proto wire layout).
"""

import glob
import os
import struct

import pytest

from distributedtensorflowexample_tpu.utils import tfevents


def test_crc32c_known_answers():
    # Canonical CRC32C check vectors (RFC 3720 / kernel test suite).
    assert tfevents.crc32c(b"123456789") == 0xE3069283
    assert tfevents.crc32c(b"") == 0x0
    assert tfevents.crc32c(b"\x00" * 32) == 0x8A9136AA


def test_varint_roundtrip():
    for n in (0, 1, 127, 128, 300, 2 ** 21, 2 ** 35, 2 ** 63 - 1):
        data = tfevents._varint(n)
        got, i = tfevents._read_varint(data, 0)
        assert got == n and i == len(data)


def test_writer_roundtrip(tmp_path):
    w = tfevents.TFEventsWriter(str(tmp_path))
    w.scalar(1, "loss", 2.5, wall_time=123.0)
    w.scalar(2, "accuracy", 0.75, wall_time=124.0)
    w.scalar(100, "loss", 0.125, wall_time=125.0)
    w.close()

    events = tfevents.read_events(w.path)
    assert events[0]["file_version"] == "brain.Event:2"
    scalars = [(e["step"], e["tag"], e["value"]) for e in events[1:]]
    assert scalars == [(1, "loss", 2.5), (2, "accuracy", 0.75),
                       (100, "loss", 0.125)]
    assert events[1]["wall_time"] == 123.0


def test_reader_rejects_corruption(tmp_path):
    w = tfevents.TFEventsWriter(str(tmp_path))
    w.scalar(1, "loss", 1.0)
    w.close()
    with open(w.path, "r+b") as f:
        f.seek(-1, os.SEEK_END)
        last = f.read(1)
        f.seek(-1, os.SEEK_END)
        f.write(bytes([last[0] ^ 0xFF]))
    with pytest.raises(ValueError, match="crc"):
        tfevents.read_events(w.path)


def test_filename_is_tensorboard_discoverable(tmp_path):
    w = tfevents.TFEventsWriter(str(tmp_path))
    w.close()
    assert os.path.basename(w.path).startswith("events.out.tfevents.")


def test_record_framing_layout(tmp_path):
    """First 12 bytes are len(u64le) + masked crc of the len bytes — the
    exact TFRecord layout, byte for byte."""
    w = tfevents.TFEventsWriter(str(tmp_path))
    w.close()
    with open(w.path, "rb") as f:
        raw = f.read()
    (length,) = struct.unpack_from("<Q", raw, 0)
    (hcrc,) = struct.unpack_from("<I", raw, 8)
    assert hcrc == tfevents.masked_crc32c(raw[:8])
    data = raw[12:12 + length]
    (dcrc,) = struct.unpack_from("<I", raw, 12 + length)
    assert dcrc == tfevents.masked_crc32c(data)


def test_overflow_value_saturates_to_inf(tmp_path):
    """A diverged loss (finite float64 > float32 max) must log as inf,
    not crash the training loop at the log boundary."""
    w = tfevents.TFEventsWriter(str(tmp_path))
    w.scalar(1, "loss", 1e39)
    w.scalar(2, "loss", -1e39)
    w.close()
    vals = [e["value"] for e in tfevents.read_events(w.path) if "value" in e]
    assert vals[0] == float("inf") and vals[1] == float("-inf")


def test_truncated_tail_returns_valid_prefix(tmp_path):
    """A killed writer leaves a partial final record; the reader must
    return the complete prefix, not raise."""
    w = tfevents.TFEventsWriter(str(tmp_path))
    w.scalar(1, "loss", 1.0)
    w.scalar(2, "loss", 0.5)
    w.close()
    with open(w.path, "rb") as f:
        raw = f.read()
    for cut in (1, 5, 11, 20):  # truncate inside the last record's frames
        with open(w.path, "wb") as f:
            f.write(raw[:-cut])
        events = tfevents.read_events(w.path)
        assert [e["value"] for e in events if "value" in e] == [1.0]


def test_negative_step_encodes_without_hang(tmp_path):
    """Proto int64 negatives are 10-byte two's complement — must encode,
    not spin forever in the varint loop."""
    data = tfevents.encode_scalar_event(0.0, -1, "t", 1.0)
    fields = {f: v for f, _w, v in tfevents._decode_fields(data)}
    assert fields[2] == 0xFFFFFFFFFFFFFFFF  # -1 as unsigned two's complement


def test_real_tensorboard_reads_our_file(tmp_path):
    """Cross-validate against TensorBoard's own event-file loader (present
    in this image): the hand-rolled framing/proto must parse as genuine
    tf.summary scalars, not just round-trip through our reader."""
    pytest.importorskip("tensorboard")
    from tensorboard.backend.event_processing import event_file_loader

    w = tfevents.TFEventsWriter(str(tmp_path))
    w.scalar(7, "loss", 1.25, wall_time=42.0)
    w.scalar(8, "accuracy", 0.5, wall_time=43.0)
    w.close()

    events = list(event_file_loader.LegacyEventFileLoader(w.path).Load())
    assert events[0].file_version == "brain.Event:2"
    scalars = [(e.step, v.tag, v.simple_value)
               for e in events[1:] for v in e.summary.value]
    assert scalars == [(7, "loss", 1.25), (8, "accuracy", 0.5)]
    assert events[1].wall_time == 42.0


def test_metrics_logger_writes_tfevents(tmp_path):
    from distributedtensorflowexample_tpu.training.metrics import MetricsLogger

    logger = MetricsLogger(str(tmp_path), num_chips=2, log_every=1)
    logger.start(0)
    logger.maybe_log(1, {"loss": 3.0, "accuracy": 0.5})
    logger.scalar(1, "eval_accuracy", 0.9)
    logger.close()

    files = glob.glob(str(tmp_path / "events.out.tfevents.*"))
    assert len(files) == 1
    events = tfevents.read_events(files[0])
    by_tag = {e["tag"]: e["value"] for e in events if "tag" in e}
    assert by_tag["loss"] == 3.0
    assert by_tag["accuracy"] == 0.5
    assert by_tag["eval_accuracy"] == pytest.approx(0.9, abs=1e-6)
    assert "steps_per_sec" in by_tag


def test_loop_excludes_hook_time_from_steps_per_sec():
    """A slow hook (eval/checkpoint stand-in) must not depress the reported
    training rate: 10 trivial steps + ~0.5s of hook sleeps must still report
    a high steps/sec."""
    import time

    from distributedtensorflowexample_tpu.training.hooks import Hook
    from distributedtensorflowexample_tpu.training.loop import TrainLoop
    from distributedtensorflowexample_tpu.training.metrics import MetricsLogger

    class SlowHook(Hook):
        def after_step(self, step, state, metrics):
            time.sleep(0.05)
            return False

    class FakeState:
        step = 0

    logger = MetricsLogger(log_every=10)
    loop = TrainLoop(lambda s, b: (s, {"loss": 0.0}), iter([None] * 10), 10,
                     hooks=[SlowHook()], logger=logger)
    loop.run(FakeState())
    # Without exclusion the window would be ~0.5s -> ~20 steps/sec.
    assert logger.last_steps_per_sec > 100


def test_logger_excludes_hook_time():
    """exclude() discounts non-training wall time from the window."""
    import time

    from distributedtensorflowexample_tpu.training.metrics import MetricsLogger

    logger = MetricsLogger(log_every=1)
    logger.start(0)
    time.sleep(0.05)          # "training" time
    logger.exclude(10.0)      # pretend a 10s hook ran — must not be counted
    logger.maybe_log(1, {"loss": 0.0})
    # 1 step in (0.05s - 10s excluded) -> negative window would explode the
    # rate; clamp behavior: with the exclusion larger than the window the
    # logger must not report a bogus *small* rate.  (The realistic case —
    # exclusion smaller than the window — is covered by the loop test.)
    assert logger.last_steps_per_sec == 0.0 or logger.last_steps_per_sec > 20
