"""Pallas kernel parity tests (interpret mode on CPU — SURVEY.md §4).

Each kernel is checked value- and gradient-exact against the pure-jnp
reference implementation it replaces.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributedtensorflowexample_tpu.ops.losses import softmax_cross_entropy
from distributedtensorflowexample_tpu.ops.pallas import (
    fused_sgd_apply, fused_softmax_cross_entropy_rows)


def _ref_rows(logits, labels, smoothing=0.0):
    num_classes = logits.shape[-1]
    onehot = jax.nn.one_hot(labels, num_classes, dtype=logits.dtype)
    if smoothing > 0.0:
        onehot = onehot * (1.0 - smoothing) + smoothing / num_classes
    return -jnp.sum(onehot * jax.nn.log_softmax(logits, axis=-1), axis=-1)


@pytest.mark.parametrize("batch,classes", [(32, 10), (64, 100), (24, 10)])
@pytest.mark.parametrize("smoothing", [0.0, 0.1])
def test_ce_rows_match_reference(batch, classes, smoothing):
    rng = np.random.RandomState(0)
    logits = jnp.asarray(rng.randn(batch, classes).astype(np.float32)) * 5
    labels = jnp.asarray(rng.randint(0, classes, size=batch, dtype=np.int32))
    got = fused_softmax_cross_entropy_rows(logits, labels, smoothing)
    want = _ref_rows(logits, labels, smoothing)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("smoothing", [0.0, 0.1])
def test_ce_gradient_matches_reference(smoothing):
    rng = np.random.RandomState(1)
    logits = jnp.asarray(rng.randn(32, 10).astype(np.float32))
    labels = jnp.asarray(rng.randint(0, 10, size=32, dtype=np.int32))

    def fused(l):
        return jnp.mean(fused_softmax_cross_entropy_rows(l, labels, smoothing))

    def ref(l):
        return softmax_cross_entropy(l, labels, smoothing)

    v1, g1 = jax.value_and_grad(fused)(logits)
    v2, g2 = jax.value_and_grad(ref)(logits)
    np.testing.assert_allclose(v1, v2, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(g1, g2, rtol=1e-5, atol=1e-6)


def test_ce_jit_and_weighted_vjp():
    # Non-uniform cotangent exercises the per-row backward scaling.
    rng = np.random.RandomState(2)
    logits = jnp.asarray(rng.randn(16, 10).astype(np.float32))
    labels = jnp.asarray(rng.randint(0, 10, size=16, dtype=np.int32))
    w = jnp.linspace(0.1, 2.0, 16)

    @jax.jit
    def fused(l):
        return jnp.sum(w * fused_softmax_cross_entropy_rows(l, labels))

    def ref(l):
        return jnp.sum(w * _ref_rows(l, labels))

    np.testing.assert_allclose(jax.grad(fused)(logits), jax.grad(ref)(logits),
                               rtol=1e-5, atol=1e-6)


def _tree():
    rng = np.random.RandomState(3)
    mk = lambda *s: jnp.asarray(rng.randn(*s).astype(np.float32))
    return {"conv": {"kernel": mk(5, 5, 1, 32), "bias": mk(32)},
            "dense": {"kernel": mk(300, 7), "bias": mk(7)}}


@pytest.mark.parametrize("mu", [0.0, 0.9])
def test_fused_sgd_matches_optax(mu):
    params, grads, mom = _tree(), _tree(), jax.tree.map(jnp.zeros_like, _tree())
    mom = jax.tree.map(lambda x: x * 0.5, _tree())
    lr = 0.13
    p_new, m_new = fused_sgd_apply(params, mom, grads, lr, mu)

    # optax.sgd(momentum=mu): m_t = mu*m + g ; update = -lr*m_t
    want_m = jax.tree.map(lambda m, g: mu * m + g, mom, grads)
    want_p = jax.tree.map(lambda p, m: p - lr * m, params, want_m)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-6),
                 p_new, want_p)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-6),
                 m_new, want_m)


def test_fused_sgd_traced_lr_under_jit():
    params, grads = _tree(), _tree()
    mom = jax.tree.map(jnp.zeros_like, params)
    sched = optax.cosine_decay_schedule(0.1, 100)

    @jax.jit
    def step(params, mom, grads, count):
        return fused_sgd_apply(params, mom, grads, sched(count), 0.9)

    p_new, m_new = step(params, mom, grads, jnp.asarray(7))
    lr = float(sched(7))
    want_p = jax.tree.map(lambda p, g: p - lr * g, params, grads)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-5),
                 p_new, want_p)


def test_pallas_step_matches_xla_step_on_mesh():
    """Full sync-DP train step with both Pallas paths on the 8-device mesh
    matches the XLA step numerically (same batch, same init)."""
    from distributedtensorflowexample_tpu.data.synthetic import make_synthetic
    from distributedtensorflowexample_tpu.models import build_model
    from distributedtensorflowexample_tpu.ops.pallas import fused_momentum_sgd
    from distributedtensorflowexample_tpu.parallel import (
        batch_sharding, make_mesh, replicated_sharding)
    from distributedtensorflowexample_tpu.parallel.sync import make_train_step
    from distributedtensorflowexample_tpu.training.state import TrainState

    mesh = make_mesh()
    x, y = make_synthetic(64, (28, 28, 1), 10, seed=0)
    batch = jax.device_put({"image": x, "label": y}, batch_sharding(mesh))
    model = build_model("softmax")

    def run(tx, **step_kw):
        state = TrainState.create_sharded(model, tx, (64, 28, 28, 1), 0,
                                          replicated_sharding(mesh))
        with mesh:
            state, metrics = make_train_step(**step_kw)(state, batch)
        return state, metrics

    s_ref, m_ref = run(optax.sgd(0.1, momentum=0.9))
    s_pal, m_pal = run(fused_momentum_sgd(0.1, momentum=0.9, mesh=mesh),
                       ce_impl="pallas", mesh=mesh)
    np.testing.assert_allclose(float(m_ref["loss"]), float(m_pal["loss"]),
                               rtol=1e-5)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-4,
                                                         atol=1e-6),
                 s_ref.params, s_pal.params)


def test_fused_sgd_is_one_kernel_launch():
    """The whole parameter set updates in ONE pallas_call (round 1 launched
    one per leaf — ~65 for ResNet-20), with the momentum trace stored as a
    single flat (rows, 128) buffer."""
    from distributedtensorflowexample_tpu.ops.pallas import fused_momentum_sgd

    tx = fused_momentum_sgd(0.1, momentum=0.9)
    params = _tree()
    state = tx.init(params)
    assert state.trace.ndim == 2 and state.trace.shape[1] == 128

    jaxpr = jax.make_jaxpr(
        lambda g, s, p: tx.update(g, s, p))(_tree(), state, params)
    assert str(jaxpr).count("pallas_call") == 1

    # Zero-momentum first step == plain SGD update.
    grads = _tree()
    updates, state2 = tx.update(grads, state, params)
    jax.tree.map(lambda u, g: np.testing.assert_allclose(u, -0.1 * g,
                                                         rtol=1e-6,
                                                         atol=1e-7),
                 updates, grads)
    assert int(state2.count) == 1


def test_fused_optimizer_flag_rejects_incompatible_config():
    from distributedtensorflowexample_tpu.config import RunConfig
    from distributedtensorflowexample_tpu.training.optimizers import (
        build_optimizer)

    with pytest.raises(ValueError, match="momentum"):
        build_optimizer(RunConfig(fused_optimizer=True, momentum=0.0))
    with pytest.raises(ValueError, match="weight_decay"):
        build_optimizer(RunConfig(fused_optimizer=True, momentum=0.9,
                                  weight_decay=1e-4))


def test_fused_optimizer_rejected_in_async_mode(tmp_path):
    """The Pallas CE head works under async (tests/test_async.py); the
    fused optimizer apply cannot (pallas under the worker vmap) and must
    fail fast with a clear error."""
    from distributedtensorflowexample_tpu.config import RunConfig
    from distributedtensorflowexample_tpu.trainers.common import run_training

    cfg = RunConfig(sync_mode="async", fused_optimizer=True, momentum=0.9,
                    train_steps=1, batch_size=64, global_batch=True,
                    dataset="synthetic", data_dir=str(tmp_path),
                    log_dir=str(tmp_path / "logs"), resume=False)
    with pytest.raises(ValueError, match="fused_optimizer"):
        run_training(cfg, "softmax", "mnist")
