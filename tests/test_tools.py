"""tools/tpu_watch.sh recovery-edge logic, tested with PATH shims.

The FAIL->OK edge branch (kill stale bench, guard against live
captures, launch exactly one capture per window) has never executed
against a real recovery — the backend was down whenever the watcher
ran — and a bug there silently loses a recovery window.  These tests
drive the real script with a shimmed `python` (probe fails once, then
OK — `prev` starts OK by design, so the edge needs a FAIL first),
`pgrep` (reports a fake stale bench and/or a live capture), `ps`
(controls the fake bench's age) and `setsid` (records the launch
instead of executing it), so no real process is probed, killed, or
spawned.
"""

import os
import signal
import subprocess
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Above the kernel's architectural pid ceiling (pid_max caps at
# 4194304), so the script's un-shimmed builtin `kill` on it can never
# hit a real process; assertions read the log line instead.
FAKE_PID = 4999999


def _write_shim(bindir, name, body):
    path = os.path.join(bindir, name)
    with open(path, "w") as f:
        f.write("#!/bin/bash\n" + body + "\n")
    os.chmod(path, 0o755)


def _run_watcher(tmp_path, *, bench_age_s=None, capture_live=False,
                 done_when, timeout_s=60, settle_s=0.0):
    """Start the real tools/tpu_watch.sh under shims and stop it once
    ``done_when(log_text)`` is true (or on timeout).  ``bench_age_s``
    not None makes the pgrep shim report FAKE_PID as a parked bench of
    that age; ``capture_live`` makes it report a live capture script.
    Returns (log_text, launches_path, marker_path)."""
    bindir = tmp_path / "bin"
    bindir.mkdir()
    launches = tmp_path / "launches.log"
    watch_log = tmp_path / "watch.log"
    marker = tmp_path / "recovered"

    state = tmp_path / "probe_state"
    _write_shim(str(bindir), "python",
                'if [ ! -f %s ]; then touch %s; echo "FAIL shim"; '
                'else echo "OK shim-probe"; fi' % (state, state))
    bench_case = ('*"python bench"*) echo %d;;' % FAKE_PID
                  if bench_age_s is not None else '')
    capture_case = ('*bench_capture*) echo %d;;' % FAKE_PID
                    if capture_live else '')
    _write_shim(str(bindir), "pgrep",
                'case "$*" in %s %s *) exit 1;; esac'
                % (bench_case, capture_case))
    _write_shim(str(bindir), "ps", 'echo " %d"' % (bench_age_s or 0))
    _write_shim(str(bindir), "setsid", 'echo "$@" >> %s' % launches)

    env = dict(os.environ,
               PATH=f"{bindir}:{os.environ['PATH']}",
               WATCH_LOG=str(watch_log),
               RECOVERED_MARKER=str(marker),
               PROBE_INTERVAL_S="1")
    proc = subprocess.Popen(["bash", os.path.join(REPO, "tools",
                                                  "tpu_watch.sh")],
                            env=env, cwd=REPO,
                            stdout=subprocess.DEVNULL,
                            stderr=subprocess.DEVNULL)
    try:
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            log = watch_log.read_text() if watch_log.exists() else ""
            if done_when(log):
                # Let a few more probe cycles run so once-per-edge
                # assertions observe the steady state, not the instant
                # of the first firing.
                time.sleep(settle_s)
                break
            time.sleep(0.5)
    finally:
        proc.send_signal(signal.SIGTERM)
        proc.wait(timeout=10)

    log = watch_log.read_text() if watch_log.exists() else ""
    return log, launches, marker


def test_recovery_edge_kills_stale_bench_and_launches_once(tmp_path):
    log, launches, marker = _run_watcher(
        tmp_path, bench_age_s=1000,   # past the 900 s stale gate
        done_when=lambda log: "launching auto-capture" in log,
        settle_s=3.0)                 # a few more OK probes: edge, not level
    assert f"killing stale bench pid {FAKE_PID}" in log
    assert "launching auto-capture" in log, log
    assert marker.exists()
    lines = launches.read_text().strip().splitlines()
    assert len(lines) == 1, lines
    assert "bench_capture.sh" in lines[0]
    assert log.count("launching auto-capture") == 1


def test_young_bench_is_left_alone(tmp_path):
    log, launches, _ = _run_watcher(
        tmp_path, bench_age_s=60,     # re-acquired the backend itself
        done_when=lambda log: "young bench" in log)
    assert "young bench already capturing; not launching" in log
    assert "killing stale bench" not in log
    assert not launches.exists()


def test_live_capture_script_suppresses_launch(tmp_path):
    log, launches, _ = _run_watcher(
        tmp_path, capture_live=True,
        done_when=lambda log: "already live" in log)
    assert "capture script already live; not launching" in log
    assert not launches.exists()
