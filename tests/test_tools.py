"""tools/tpu_watch.sh recovery-edge logic, tested with PATH shims.

The FAIL->OK edge branch (stale capture/bench cleanup, live-capture
suppression, exactly-one launch per window) has never executed against
a real recovery — the backend was down whenever the watcher ran — and a
bug there silently loses a recovery window.  These tests drive the real
script with a shimmed `python` (probe fails once then OK, or always
OK), `pgrep` (reports a fake bench), `ps` (controls fake process ages /
liveness) and `setsid` (records the launch instead of executing it), so
no real process is probed, killed, or spawned.

Capture liveness is a PIDFILE (written by bench_capture.sh), not argv
matching — see test_capture_pidfile_written_for_any_launch_spelling for
the round-3 weak item (non-canonical spellings were invisible).
"""

import os
import signal
import subprocess
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Above the kernel's architectural pid ceiling (pid_max caps at
# 4194304), so the script's un-shimmed builtin `kill` on them can never
# hit a real process; assertions read the log line instead.
FAKE_BENCH_PID = 4999999
FAKE_CAP_PID = 4999998


def _write_shim(bindir, name, body):
    path = os.path.join(bindir, name)
    with open(path, "w") as f:
        f.write("#!/bin/bash\n" + body + "\n")
    os.chmod(path, 0o755)


def _run_watcher(tmp_path, *, bench_age_s=None, cap_age_s=None,
                 probe="fail_once", stale_s=None, done_when, timeout_s=60,
                 settle_s=0.0, extra_env=None):
    """Start the real tools/tpu_watch.sh under shims and stop it once
    ``done_when(log_text)`` is true (or on timeout).

    ``bench_age_s``: not None -> the pgrep shim reports FAKE_BENCH_PID
    as a parked `python bench.py` of that age.
    ``cap_age_s``: not None -> a pidfile naming FAKE_CAP_PID exists;
    the ps shim reports that age, or nothing (dead pid) for "dead".
    ``probe``: "fail_once" (a FLAP: one FAIL then OK — kills must stay
    disarmed), "fail_twice" (a CONFIRMED outage: kills armed on the
    edge), or "always_ok" (healthy-window start).
    Returns (log_text, launches_path, marker_path, pidfile_path)."""
    bindir = tmp_path / "bin"
    bindir.mkdir()
    launches = tmp_path / "launches.log"
    watch_log = tmp_path / "watch.log"
    marker = tmp_path / "recovered"
    pidfile = tmp_path / "bench_capture.pid"

    if cap_age_s is not None:
        pidfile.write_text(str(FAKE_CAP_PID))

    state = tmp_path / "probe_state"
    n_fails = {"fail_once": 1, "fail_twice": 2, "always_ok": 0}[probe]
    _write_shim(str(bindir), "python",
                'n=$(cat %s 2>/dev/null || echo 0); n=$((n+1)); '
                'echo $n > %s; '
                'if [ "$n" -le %d ]; then echo "FAIL shim"; '
                'else echo "OK shim-probe"; fi' % (state, state, n_fails))
    bench_case = ('*"python bench"*) echo %d;;' % FAKE_BENCH_PID
                  if bench_age_s is not None else '')
    _write_shim(str(bindir), "pgrep",
                'case "$*" in %s *) exit 1;; esac' % bench_case)
    cap_ps = ('echo " %s"' % cap_age_s
              if cap_age_s not in (None, "dead") else ':')
    _write_shim(str(bindir), "ps",
                'case "$*" in *%d*) %s;; *%d*) echo " %s";; *) echo " 0";; '
                'esac' % (FAKE_CAP_PID, cap_ps, FAKE_BENCH_PID,
                          bench_age_s or 0))
    _write_shim(str(bindir), "setsid", 'echo "$@" >> %s' % launches)

    env = dict(os.environ,
               PATH=f"{bindir}:{os.environ['PATH']}",
               WATCH_LOG=str(watch_log),
               RECOVERED_MARKER=str(marker),
               CAPTURE_PIDFILE=str(pidfile),
               PROBE_INTERVAL_S="1",
               # Shrink the stale-kill TERM->KILL grace (default 35 s —
               # sized to outlast the supervisor's child escalation)
               # so kill-path tests finish inside their polling windows.
               CAPTURE_KILL_GRACE_S="2")
    if stale_s is not None:
        env["STALE_S"] = str(stale_s)
    env.update(extra_env or {})
    proc = subprocess.Popen(["bash", os.path.join(REPO, "tools",
                                                  "tpu_watch.sh")],
                            env=env, cwd=REPO,
                            stdout=subprocess.DEVNULL,
                            stderr=subprocess.DEVNULL)
    try:
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            log = watch_log.read_text() if watch_log.exists() else ""
            if done_when(log):
                # Let a few more probe cycles run so once-per-edge
                # assertions observe the steady state, not the instant
                # of the first firing.
                time.sleep(settle_s)
                break
            time.sleep(0.5)
    finally:
        proc.send_signal(signal.SIGTERM)
        proc.wait(timeout=10)

    log = watch_log.read_text() if watch_log.exists() else ""
    return log, launches, marker, pidfile


def test_recovery_edge_kills_stale_bench_and_launches_once(tmp_path):
    log, launches, marker, _ = _run_watcher(
        tmp_path, bench_age_s=1000,   # past the 900 s stale gate
        probe="fail_twice",           # confirmed outage: kills armed
        done_when=lambda log: "launching auto-capture" in log,
        settle_s=3.0)                 # a few more OK probes: edge, not level
    assert f"killing stale bench pid {FAKE_BENCH_PID}" in log
    assert "launching auto-capture" in log, log
    assert marker.exists()
    lines = launches.read_text().strip().splitlines()
    assert len(lines) == 1, lines
    # Default launcher is the SUPERVISED capture (journaled resume);
    # CAPTURE_LAUNCHER=bash selects the legacy inline phases.
    assert "supervise.py --capture" in lines[0]
    assert log.count("launching auto-capture") == 1


def test_recovery_edge_bash_fallback_launcher(tmp_path):
    """CAPTURE_LAUNCHER=bash keeps the battle-tested inline
    bench_capture.sh path behind the flag."""
    log, launches, _, _ = _run_watcher(
        tmp_path, bench_age_s=1000, probe="fail_twice",
        done_when=lambda log: "launching auto-capture" in log,
        settle_s=3.0, extra_env={"CAPTURE_LAUNCHER": "bash"})
    assert "launching auto-capture (bash fallback)" in log
    lines = launches.read_text().strip().splitlines()
    assert len(lines) == 1 and "bench_capture.sh" in lines[0]


def test_single_flap_edge_never_kills(tmp_path):
    """One failed probe can be a host load spike, not an outage: the
    edge must NOT kill a long-running bench (e.g. the driver's own
    official ~23-min run) — it is treated as the live capture."""
    log, launches, _, _ = _run_watcher(
        tmp_path, bench_age_s=1000,   # would be "stale" if kills were armed
        probe="fail_once",
        done_when=lambda log: "young bench" in log)
    assert "killing" not in log
    assert not launches.exists()


def test_young_bench_is_left_alone(tmp_path):
    # PROBE_TIMEOUT_S as a FLOAT: valid for the python probe consumer,
    # and the watcher's derived outer timeout must truncate it rather
    # than fatally erroring in bash arithmetic (which would turn every
    # probe into a permanent FAIL).
    log, launches, _, _ = _run_watcher(
        tmp_path, bench_age_s=60,     # re-acquired the backend itself
        done_when=lambda log: "young bench" in log,
        extra_env={"PROBE_TIMEOUT_S": "2.5"})
    assert "young bench already capturing; not launching" in log
    assert "killing stale bench" not in log
    assert not launches.exists()


def test_live_young_capture_suppresses_launch(tmp_path):
    """A live capture is recognised via its PIDFILE (no argv matching),
    whatever spelling launched it."""
    log, launches, _, pidfile = _run_watcher(
        tmp_path, cap_age_s=120,
        done_when=lambda log: "already live" in log)
    assert f"capture already live (pid {FAKE_CAP_PID}" in log
    assert not launches.exists()
    assert pidfile.exists()           # a live capture's pidfile stays


def test_stale_capture_group_killed_and_fresh_launch(tmp_path):
    """Round-3 ADVICE shape: a half-dead capture from the PREVIOUS
    window must not suppress this window's launch — the watcher kills
    the whole group and launches fresh."""
    log, launches, _, pidfile = _run_watcher(
        tmp_path, cap_age_s=2000,     # predates the window
        probe="fail_twice",           # confirmed outage: kills armed
        done_when=lambda log: "launching auto-capture" in log,
        settle_s=3.0)
    assert f"killing stale capture group {FAKE_CAP_PID}" in log
    assert log.count("launching auto-capture") == 1
    assert launches.read_text().count("supervise.py --capture") == 1
    assert not pidfile.exists()       # stale pidfile cleaned by watcher


def test_orphan_pidfile_cleaned_then_launch(tmp_path):
    """A pidfile whose process died (crash — EXIT trap never ran) must
    not block the window: clean it, then launch."""
    log, launches, _, pidfile = _run_watcher(
        tmp_path, cap_age_s="dead",
        done_when=lambda log: "launching auto-capture" in log)
    assert f"removing orphan capture pidfile (pid {FAKE_CAP_PID} dead)" in log
    assert "launching auto-capture" in log
    assert not pidfile.exists()


def test_healthy_window_start_launches_capture(tmp_path):
    """Round-3 weak item: a watcher (re)started inside an ALREADY-
    HEALTHY window never launched anything.  Now: first probe OK + no
    live capture/bench -> exactly one launch, no kills."""
    log, launches, marker, _ = _run_watcher(
        tmp_path, probe="always_ok",
        done_when=lambda log: "launching auto-capture" in log,
        settle_s=3.0)
    assert marker.exists()
    assert log.count("launching auto-capture") == 1
    assert "killing" not in log


def test_capture_pidfile_written_for_any_launch_spelling(tmp_path):
    """Run the REAL bench_capture.sh via a NON-CANONICAL spelling
    (relative `./tools/...` path through `sh`, not the watcher's
    `bash tools/bench_capture.sh`) with python shimmed to a sleeper:
    the pidfile must appear while it runs and vanish on exit — the
    property that makes the watcher spelling-independent."""
    bindir = tmp_path / "bin"
    bindir.mkdir()
    # Both bench.py and bench_profile.py invocations become short sleeps;
    # tar/du never run (profile rc=0 but no trace dir is created).
    _write_shim(str(bindir), "python", 'sleep 3')
    pidfile = tmp_path / "cap.pid"
    out = tmp_path / "b.json"
    env = dict(os.environ,
               PATH=f"{bindir}:{os.environ['PATH']}",
               CAPTURE_PIDFILE=str(pidfile),
               OUT=str(out), PROFILE_OUT=str(tmp_path / "p.json"),
               TRACE_TGZ=str(tmp_path / "t.tgz"),
               # Keep the script's `rm -rf $TRACE_DIR` inside tmp_path —
               # the default is /tmp/resnet_trace, which may hold a real
               # unarchived trace on the bench host.
               TRACE_DIR=str(tmp_path / "trace"),
               LOG=str(tmp_path / "cap.log"))
    proc = subprocess.Popen(["sh", "./tools/bench_capture.sh"],
                            env=env, cwd=REPO,
                            stdout=subprocess.DEVNULL,
                            stderr=subprocess.DEVNULL)
    try:
        deadline = time.time() + 30
        while time.time() < deadline and not pidfile.exists():
            time.sleep(0.1)
        assert pidfile.exists()
        assert pidfile.read_text().strip() == str(proc.pid)
        proc.wait(timeout=30)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=10)
    assert not pidfile.exists()       # EXIT trap cleaned its own pidfile


def test_kill_threshold_floors_at_outage_duration(tmp_path):
    """kill_over = max(STALE_S, outage_duration + 60 s): even with a tiny
    STALE_S, a bench YOUNGER than the outage window must survive the edge
    — it started DURING the outage (e.g. a parked bench in its own
    probe-retry loop) and is about to become the capture."""
    log, launches, _, _ = _run_watcher(
        tmp_path, bench_age_s=30, probe="fail_twice", stale_s=1,
        done_when=lambda log: "young bench" in log)
    assert "young bench already capturing; not launching" in log
    assert "killing" not in log
    assert not launches.exists()
