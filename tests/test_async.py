"""Async-PS emulation (local SGD) — config 2 semantics (SURVEY.md §7 step 6)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from distributedtensorflowexample_tpu.data.synthetic import make_synthetic
from distributedtensorflowexample_tpu.models import build_model
from distributedtensorflowexample_tpu.parallel import (
    batch_sharding, make_mesh, replicated_sharding)
from distributedtensorflowexample_tpu.parallel.async_ps import (
    consolidate, make_async_train_step, make_worker_state)
from distributedtensorflowexample_tpu.training.state import TrainState


def _tiled_state(mesh, lr=0.2, seed=0):
    model = build_model("softmax")
    state = TrainState.create_sharded(model, optax.sgd(lr), (8, 28, 28, 1),
                                      seed, replicated_sharding(mesh))
    return make_worker_state(state, mesh.size, mesh)


def _batch(mesh, n, seed=0, sample_seed=None):
    x, y = make_synthetic(n, (28, 28, 1), 10, seed=seed,
                          sample_seed=sample_seed)
    return jax.device_put({"image": x, "label": y}, batch_sharding(mesh))


def test_worker_state_tiled_and_sharded():
    mesh = make_mesh()
    state = _tiled_state(mesh)
    leaf = jax.tree.leaves(state.params)[0]
    assert leaf.shape[0] == 8
    assert not leaf.sharding.is_fully_replicated
    # All workers start from identical copies.
    host = jax.device_get(leaf)
    for w in range(1, 8):
        np.testing.assert_array_equal(host[0], host[w])


def test_workers_diverge_then_average():
    mesh = make_mesh()
    state = _tiled_state(mesh)
    step = make_async_train_step(mesh.size, period=4)
    for i in range(3):  # steps 1..3: no averaging yet
        state, _ = step(state, _batch(mesh, 64, sample_seed=10 + i))
    leaf = jax.device_get(jax.tree.leaves(state.params)[0])
    assert not np.array_equal(leaf[0], leaf[1])  # diverged (different shards)
    state, _ = step(state, _batch(mesh, 64, sample_seed=99))  # step 4: average
    leaf = jax.device_get(jax.tree.leaves(state.params)[0])
    np.testing.assert_allclose(leaf[0], leaf[1], rtol=1e-6, atol=1e-7)


def test_period_one_matches_sync_semantics():
    """period=1 averages every step — gradient-mean == sync SGD up to fp."""
    mesh = make_mesh()
    state = _tiled_state(mesh, lr=0.1)
    step = make_async_train_step(mesh.size, period=1)
    state, metrics = step(state, _batch(mesh, 64))
    assert np.isfinite(float(metrics["loss"]))
    leaf = jax.device_get(jax.tree.leaves(state.params)[0])
    np.testing.assert_allclose(leaf[0], leaf[7], rtol=1e-6, atol=1e-7)


def test_async_converges_and_consolidates():
    mesh = make_mesh()
    state = _tiled_state(mesh, lr=0.3)
    step = make_async_train_step(mesh.size, period=4)
    x, y = make_synthetic(64 * 20, (28, 28, 1), 10, seed=0)
    losses = []
    for i in range(20):
        sl = slice(i * 64, (i + 1) * 64)
        batch = jax.device_put({"image": x[sl], "label": y[sl]},
                               batch_sharding(mesh))
        state, metrics = step(state, batch)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0] * 0.7
    merged = consolidate(state)
    leaf = jax.tree.leaves(merged.params)[0]
    assert leaf.ndim == jax.tree.leaves(state.params)[0].ndim - 1


def test_async_trainer_end_to_end(tmp_path):
    from distributedtensorflowexample_tpu.trainers import trainer_ps_mnist
    summary = trainer_ps_mnist.main(
        ["--sync_mode", "async", "--async_period", "4",
         "--train_steps", "30", "--batch_size", "8",
         "--log_dir", str(tmp_path), "--data_dir", "/nonexistent",
         "--resume", "false", "--log_every", "10",
         "--learning_rate", "0.02"])
    assert summary["steps"] == 30
    assert np.isfinite(summary["final_accuracy"])
