"""Async-PS emulation (local SGD) — config 2 semantics (SURVEY.md §7 step 6)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from distributedtensorflowexample_tpu.data.synthetic import make_synthetic
from distributedtensorflowexample_tpu.models import build_model
from distributedtensorflowexample_tpu.parallel import (
    batch_sharding, make_mesh, replicated_sharding)
from distributedtensorflowexample_tpu.parallel.async_ps import (
    consolidate, make_async_train_step, make_indexed_async_train_step,
    make_worker_state)
from distributedtensorflowexample_tpu.training.state import TrainState


def _tiled_state(mesh, lr=0.2, seed=0):
    model = build_model("softmax")
    state = TrainState.create_sharded(model, optax.sgd(lr), (8, 28, 28, 1),
                                      seed, replicated_sharding(mesh))
    return make_worker_state(state, mesh.size, mesh)


def _batch(mesh, n, seed=0, sample_seed=None):
    x, y = make_synthetic(n, (28, 28, 1), 10, seed=seed,
                          sample_seed=sample_seed)
    return jax.device_put({"image": x, "label": y}, batch_sharding(mesh))


def test_worker_state_tiled_and_sharded():
    mesh = make_mesh()
    state = _tiled_state(mesh)
    leaf = jax.tree.leaves(state.params)[0]
    assert leaf.shape[0] == mesh.size   # one virtual worker per device
    assert not leaf.sharding.is_fully_replicated
    # All workers start from identical copies.
    host = jax.device_get(leaf)
    for w in range(1, mesh.size):
        np.testing.assert_array_equal(host[0], host[w])


def test_workers_diverge_then_average():
    mesh = make_mesh()
    state = _tiled_state(mesh)
    step = make_async_train_step(mesh.size, period=4)
    for i in range(3):  # steps 1..3: no averaging yet
        state, _ = step(state, _batch(mesh, 64, sample_seed=10 + i))
    leaf = jax.device_get(jax.tree.leaves(state.params)[0])
    assert not np.array_equal(leaf[0], leaf[1])  # diverged (different shards)
    state, _ = step(state, _batch(mesh, 64, sample_seed=99))  # step 4: average
    leaf = jax.device_get(jax.tree.leaves(state.params)[0])
    np.testing.assert_allclose(leaf[0], leaf[1], rtol=1e-6, atol=1e-7)


def test_period_one_matches_sync_semantics():
    """period=1 averages every step — gradient-mean == sync SGD up to fp."""
    mesh = make_mesh()
    state = _tiled_state(mesh, lr=0.1)
    step = make_async_train_step(mesh.size, period=1)
    state, metrics = step(state, _batch(mesh, 64))
    assert np.isfinite(float(metrics["loss"]))
    leaf = jax.device_get(jax.tree.leaves(state.params)[0])
    np.testing.assert_allclose(leaf[0], leaf[-1], rtol=1e-6, atol=1e-7)


def test_async_converges_and_consolidates():
    mesh = make_mesh()
    state = _tiled_state(mesh, lr=0.3)
    step = make_async_train_step(mesh.size, period=4)
    x, y = make_synthetic(64 * 20, (28, 28, 1), 10, seed=0)
    losses = []
    for i in range(20):
        sl = slice(i * 64, (i + 1) * 64)
        batch = jax.device_put({"image": x[sl], "label": y[sl]},
                               batch_sharding(mesh))
        state, metrics = step(state, batch)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0] * 0.7
    merged = consolidate(state)
    leaf = jax.tree.leaves(merged.params)[0]
    assert leaf.ndim == jax.tree.leaves(state.params)[0].ndim - 1


def test_async_trainer_end_to_end(tmp_path, small_synthetic):
    """trainer_ps_mnist's default path: async local-SGD over the
    device-resident dataset (config 2 out of the box)."""
    from distributedtensorflowexample_tpu.trainers import trainer_ps_mnist
    summary = trainer_ps_mnist.main(
        ["--async_period", "4",
         "--train_steps", "30", "--batch_size", "8",
         "--log_dir", str(tmp_path), "--data_dir", "/nonexistent",
         "--dataset", "synthetic",
         "--resume", "false", "--log_every", "10",
         "--learning_rate", "0.02"])
    assert summary["steps"] == 30
    assert np.isfinite(summary["final_accuracy"])


def test_indexed_async_unrolled_matches_stepwise():
    """Device-resident async: K fused updates == K separate updates
    bit-for-bit, across an epoch boundary and an averaging boundary."""
    from distributedtensorflowexample_tpu.data import DeviceDataset

    mesh = make_mesh()
    x, y = make_synthetic(384, (28, 28, 1), 10, seed=1)  # 6 steps/epoch @64
    b, K, total, period = 64, 4, 12, 3
    ds1 = DeviceDataset(x, y, b, mesh=mesh, seed=6)
    dsK = DeviceDataset(x, y, b, mesh=mesh, seed=6, steps_per_next=K)
    s1, sK = _tiled_state(mesh, lr=0.1, seed=2), _tiled_state(mesh, lr=0.1,
                                                              seed=2)
    one = make_indexed_async_train_step(mesh.size, period, b, 6, mesh=mesh)
    fused = make_indexed_async_train_step(mesh.size, period, b, 6, mesh=mesh,
                                          unroll_steps=K)
    with mesh:
        for _ in range(total):
            s1, _ = one(s1, next(ds1))
        for _ in range(total // K):
            sK, _ = fused(sK, next(dsK))
    assert int(s1.step) == int(sK.step) == total
    jax.tree.map(lambda a, c: np.testing.assert_array_equal(a, c),
                 s1.params, sK.params)


def test_shard_map_path_rejects_partial_workers():
    """The multi-device shard_map body owns whole workers per device."""
    import pytest

    mesh = make_mesh()
    with pytest.raises(ValueError, match="multiple of the mesh size"):
        make_async_train_step(mesh.size + 1, period=2, mesh=mesh)


def test_vmap_and_shard_map_paths_agree():
    """The explicit shard_map body computes the same math as the GSPMD-
    partitioned vmap body (fp tolerance: reductions reorder)."""
    mesh = make_mesh()
    s_v, s_s = _tiled_state(mesh, lr=0.2, seed=5), _tiled_state(mesh, lr=0.2,
                                                                seed=5)
    step_v = make_async_train_step(mesh.size, period=2)            # vmap
    step_s = make_async_train_step(mesh.size, period=2, mesh=mesh)  # shard_map
    with mesh:
        for sample_seed in (8, 9):  # step 2 crosses the averaging point
            b = _batch(mesh, 64, sample_seed=sample_seed)
            s_v, m_v = step_v(s_v, b)
            s_s, m_s = step_s(s_s, b)
    np.testing.assert_allclose(float(m_v["loss"]), float(m_s["loss"]),
                               rtol=1e-5)
    np.testing.assert_allclose(float(m_v["accuracy"]),
                               float(m_s["accuracy"]), rtol=1e-6)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-4,
                                                         atol=1e-6),
                 s_v.params, s_s.params)


def test_async_pallas_ce_matches_xla():
    """The Pallas loss head under async (flattened-batch shard_map) is
    numerically equivalent to the XLA head."""
    mesh = make_mesh()
    batch = _batch(mesh, 64, sample_seed=3)
    s_x, s_p = _tiled_state(mesh, lr=0.2, seed=4), _tiled_state(mesh, lr=0.2,
                                                                seed=4)
    step_x = make_async_train_step(mesh.size, period=2, ce_impl="xla",
                                   mesh=mesh)
    step_p = make_async_train_step(mesh.size, period=2, ce_impl="pallas",
                                   mesh=mesh)
    with mesh:
        s_x, m_x = step_x(s_x, batch)
        s_p, m_p = step_p(s_p, batch)
    np.testing.assert_allclose(float(m_x["loss"]), float(m_p["loss"]),
                               rtol=1e-5)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-4,
                                                         atol=1e-6),
                 s_x.params, s_p.params)


def test_run_training_async_device_data_steps_per_loop(tmp_path,
                                                       small_synthetic):
    """The three round-1 fences are gone: async + device_data +
    steps_per_loop + pallas_ce compose in one run."""
    from distributedtensorflowexample_tpu.config import RunConfig
    from distributedtensorflowexample_tpu.trainers.common import run_training

    out = run_training(
        RunConfig(sync_mode="async", async_period=4, steps_per_loop=4,
                  device_data="on", pallas_ce=True, train_steps=24,
                  batch_size=64, global_batch=True, learning_rate=0.3,
                  data_dir=str(tmp_path), log_dir=str(tmp_path / "logs"),
                  dataset="synthetic", log_every=8, seed=1, resume=False),
        "softmax", "mnist")
    assert out["steps"] == 24
    assert np.isfinite(out["final_accuracy"])
