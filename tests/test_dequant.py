"""The round-5 dequant-tax fix: affine fast path + fused kernels.

Four contracts, each pinned bitwise (compared as integer bit patterns —
"close" is not a thing this file asserts):

1. EXACTNESS — the fused affine ``f32(u) * scale + bias`` reproduces all
   256 LUT entries of every shipped loader spec, on the host and through
   this backend's jit (the verification that lets ``dequant_impl="auto"``
   lower to the fast path without giving up the bitwise-parity
   guarantee).
2. PARITY — training through the affine impl equals training through the
   LUT impls bit-for-bit on params, across every data path: replicated
   resident, sharded resident, async local-SGD, and host-fed.
3. LOWERING — the default auto path on MNIST/CIFAR-shaped splits
   contains NO 256-entry gather in its jaxpr (the exact op the round-5
   window measured at ~10 ns/element — AB_quantize_r05.json: 479.6 vs
   1,962.6 steps/s/chip same-window), with a positive control proving
   the detector sees the gather when it IS there.
4. KERNELS — the fused Pallas gather+dequant and the fused
   augment+dequant emit bitwise-identical batches to their unfused
   forms (interpret mode on CPU: same kernel code the TPU runs).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributedtensorflowexample_tpu.data import DeviceDataset
from distributedtensorflowexample_tpu.data.dequant import (
    affine_matches_lut, affine_numpy, make_dequant_affine, make_dequant_lut)
from distributedtensorflowexample_tpu.data.device_dataset import (
    apply_dequant_affine, dequant_affine_is_bitwise, resolve_dequant_impl)
from distributedtensorflowexample_tpu.data.synthetic import make_synthetic
from distributedtensorflowexample_tpu.models import build_model
from distributedtensorflowexample_tpu.parallel import (
    make_mesh, replicated_sharding)
from distributedtensorflowexample_tpu.parallel.sync import (
    make_device_gather, make_indexed_train_step, make_resident_eval,
    make_train_step)
from distributedtensorflowexample_tpu.training.state import TrainState

SPECS = ("unit", "cifar")


def _bitwise_equal(a, b):
    a, b = np.asarray(a), np.asarray(b)
    assert a.dtype == b.dtype == np.float32
    np.testing.assert_array_equal(a.view(np.int32), b.view(np.int32))


def _data(n=320, shape=(28, 28, 1), seed=0):
    # NOT 256 rows: a [256]-shaped labels vector (or a 256-row split) is
    # indistinguishable from a LUT table by operand shape alone, and the
    # jaxpr detector below must not flag the legitimate row gathers.
    return make_synthetic(n, shape, 10, seed=seed)


def _cifar_normalized(x):
    """Normalize [0,1] byte-grid pixels the way load_cifar10 does: through
    the canonical single-rounding affine (data.dequant) — NOT a separate
    f32 (x - MEAN) / STD, which double-rounds and is not byte-exact."""
    return affine_numpy(np.rint(x * 255.0).astype(np.uint8), "cifar")


# ---- 1. exactness: affine == LUT over all 256 entries -------------------

@pytest.mark.parametrize("spec", SPECS)
def test_affine_reproduces_all_256_lut_entries_bitwise(spec):
    """The quantize-time verification, spelled out: every byte value's
    affine image equals its tabulated loader value, bit for bit."""
    lut = make_dequant_lut(spec)
    u = np.arange(256, dtype=np.uint8)[:, None]
    aff = affine_numpy(u, spec)
    aff = aff[:, 0] if lut.ndim == 1 else aff
    assert lut.dtype == aff.dtype == np.float32
    np.testing.assert_array_equal(lut.view(np.int32),
                                  np.ascontiguousarray(aff).view(np.int32))
    assert affine_matches_lut(spec)


@pytest.mark.parametrize("spec", SPECS)
def test_backend_affine_is_bitwise(spec):
    """The backend half of the auto-lowering guard: THIS backend's jitted
    fused multiply-add reproduces the table too (a backend that split the
    fma into mul+add would double-round and must fail this)."""
    assert dequant_affine_is_bitwise(spec)
    lut = make_dequant_lut(spec)
    s, b = make_dequant_affine(spec)
    u = np.arange(256, dtype=np.uint8)
    if lut.ndim == 2:
        u = np.broadcast_to(u[:, None], (256, lut.shape[1]))
    got = jax.jit(apply_dequant_affine)(jnp.asarray(u), jnp.asarray(s),
                                        jnp.asarray(b))
    _bitwise_equal(got, np.ascontiguousarray(lut))


def test_resolve_dequant_impl_rules(monkeypatch):
    """auto lowers to affine exactly when the spec is affine-exact;
    otherwise the bitwise one-hot fallback (unless the caller asked for
    speed-over-bits via quantize='scale'); named impls pass through."""
    for spec in SPECS:
        assert resolve_dequant_impl(spec) == "affine"
    for forced in ("affine", "onehot", "lut", "pallas"):
        assert resolve_dequant_impl("unit", forced) == forced
    with pytest.raises(ValueError, match="dequant_impl"):
        resolve_dequant_impl("unit", "bogus")
    # A hypothetical non-affine-representable spec (e.g. a gamma curve):
    # auto must keep the bitwise contract through onehot.
    from distributedtensorflowexample_tpu.data import device_dataset as dd
    monkeypatch.setattr(dd, "affine_matches_lut", lambda spec: False)
    assert resolve_dequant_impl("unit", "auto", "auto") == "onehot"
    assert resolve_dequant_impl("unit", "auto", "exact") == "onehot"
    assert resolve_dequant_impl("unit", "auto", "scale") == "affine"


# ---- 2. bitwise training parity across every data path ------------------

def _train_replicated(impl, x, y, mesh, steps_per_next=2, calls=3,
                      data_sharding="replicated"):
    ds = DeviceDataset(x, y, 32, mesh=mesh, seed=2, quantize="auto",
                       dequant_impl=impl, steps_per_next=steps_per_next,
                       data_sharding=data_sharding)
    assert ds.dequant == "unit"
    state = TrainState.create_sharded(build_model("softmax"),
                                      optax.sgd(0.1), (32, 28, 28, 1), 0,
                                      replicated_sharding(mesh))
    step = make_indexed_train_step(32, ds.steps_per_epoch, mesh=mesh,
                                   unroll_steps=steps_per_next,
                                   num_slots=ds.num_slots,
                                   data_sharding=data_sharding,
                                   dequant_impl=impl)
    with mesh:
        for _ in range(calls):
            state, metrics = step(state, next(ds))
        jax.block_until_ready(metrics)
    return np.asarray(jax.tree.leaves(state.params)[0]), float(
        metrics["loss"])


@pytest.mark.parametrize("other", ["onehot", "lut"])
def test_training_parity_affine_vs_lut_replicated(other):
    x, y = _data()
    mesh = make_mesh()
    p_a, l_a = _train_replicated("affine", x, y, mesh)
    p_o, l_o = _train_replicated(other, x, y, mesh)
    assert l_a == l_o
    np.testing.assert_array_equal(p_a, p_o)


def test_training_parity_affine_vs_lut_sharded():
    x, y = _data(512)
    mesh = make_mesh()
    p_a, l_a = _train_replicated("affine", x, y, mesh,
                                 data_sharding="sharded")
    p_o, l_o = _train_replicated("onehot", x, y, mesh,
                                 data_sharding="sharded")
    assert l_a == l_o
    np.testing.assert_array_equal(p_a, p_o)


def test_training_parity_affine_vs_lut_async():
    from distributedtensorflowexample_tpu.parallel.async_ps import (
        make_indexed_async_train_step, make_worker_state)

    x, y = _data(512)
    mesh = make_mesh()

    def run(impl):
        ds = DeviceDataset(x, y, 64, mesh=mesh, seed=5, steps_per_next=4,
                           dequant_impl=impl)
        state = TrainState.create_sharded(
            build_model("softmax"), optax.sgd(0.1), (64, 28, 28, 1), 0,
            replicated_sharding(mesh))
        state = make_worker_state(state, mesh.size, mesh)
        step = make_indexed_async_train_step(
            mesh.size, 4, 64, ds.steps_per_epoch, mesh=mesh,
            unroll_steps=4, num_slots=ds.num_slots, dequant_impl=impl)
        with mesh:
            state, m = step(state, next(ds))
            state, m = step(state, next(ds))
            jax.block_until_ready(m)
        return np.asarray(jax.tree.leaves(state.params)[0])

    np.testing.assert_array_equal(run("affine"), run("onehot"))


def test_training_parity_affine_vs_lut_host_fed():
    """dequant_host_batch resolves the SAME impl knob: a uint8 host batch
    trained through affine equals onehot and lut bit-for-bit (pallas
    degenerates to affine — no gather to fuse with on an upload)."""
    x, y = _data(64)
    u8 = np.rint(x * 255.0).astype(np.uint8)

    def run(impl):
        state = TrainState.create(build_model("softmax"), optax.sgd(0.1),
                                  np.zeros((64, 28, 28, 1), np.float32))
        step = make_train_step(dequant="unit", dequant_impl=impl)
        batch = {"image": jnp.asarray(u8), "label": jnp.asarray(y)}
        for _ in range(3):
            state, m = step(state, batch)
        jax.block_until_ready(m)
        return np.asarray(jax.tree.leaves(state.params)[0])

    ref = run("affine")
    for other in ("onehot", "lut", "pallas", "auto"):
        np.testing.assert_array_equal(ref, run(other))


def test_gather_rejects_mismatched_factory_and_dataset():
    """A step factory forced to one impl family over a dataset resolved
    to the other is a TRACE-TIME error, not a silently different kernel
    (the train/eval-asymmetry hazard, caught at build)."""
    x, y = _data()
    ds = DeviceDataset(x, y, 32, seed=0, dequant_impl="affine")
    g = make_device_gather(32, ds.steps_per_epoch, num_slots=ds.num_slots,
                           dequant_impl="onehot")
    with pytest.raises(ValueError, match="affine family"):
        jax.jit(g)(jnp.asarray(0, jnp.int32), jax.random.PRNGKey(0),
                   ds.peek())
    ds_l = DeviceDataset(x, y, 32, seed=0, dequant_impl="lut")
    g_a = make_device_gather(32, ds_l.steps_per_epoch,
                             num_slots=ds_l.num_slots, dequant_impl="affine")
    with pytest.raises(ValueError, match="LUT family"):
        jax.jit(g_a)(jnp.asarray(0, jnp.int32), jax.random.PRNGKey(0),
                     ds_l.peek())


def test_resident_eval_honors_dequant_impl():
    """Eval resolves the SAME rule as training, so a train/eval parity
    check exercises one kernel — and every impl yields the identical
    accuracy (the dequants are bitwise-equal, so the logits are too)."""
    x, y = _data(200)
    state = TrainState.create(build_model("softmax"), optax.sgd(0.1),
                              np.zeros((50, 28, 28, 1), np.float32))
    accs = {impl: make_resident_eval(x, y, batch_size=50,
                                     dequant_impl=impl)(state)
            for impl in ("auto", "affine", "onehot", "lut", "pallas")}
    assert len(set(accs.values())) == 1, accs


# ---- 3. lowering: the default auto path has no 256-entry gather ---------

def _gather_eqns(jaxpr):
    """Every gather-family eqn in ``jaxpr`` (recursively through inner
    jaxprs) whose first operand is LUT-shaped — [256] or [256, C] — the
    table read the affine lowering exists to eliminate.  The ndim cap
    keeps a legitimate row gather over a 256-row split
    (``take(images[256, H, W, C], idx)``) out of the net; a [256] LABELS
    vector is shape-indistinguishable from a unit LUT, which is why
    ``_data`` defaults to 320 rows."""
    from jax import core
    found = []

    def walk(jx):
        for eqn in jx.eqns:
            if "gather" in eqn.primitive.name:
                shapes = [tuple(getattr(v.aval, "shape", ())) or ()
                          for v in eqn.invars]
                if any(s and s[0] == 256 and len(s) <= 2 for s in shapes):
                    found.append((eqn.primitive.name, shapes))
        for sub in core.subjaxprs(jx):
            walk(sub)

    walk(jaxpr.jaxpr if hasattr(jaxpr, "jaxpr") else jaxpr)
    return found


@pytest.mark.parametrize("shape,spec", [((28, 28, 1), "unit"),
                                        ((32, 32, 3), "cifar")])
def test_default_auto_path_has_no_256_gather(shape, spec):
    """The acceptance-criteria jaxpr check: quantize=auto + dequant_impl=
    auto on an MNIST/CIFAR-shaped split traces to a program with NO
    256-entry table gather."""
    x, y = _data(shape=shape)
    if spec == "cifar":
        x = _cifar_normalized(x)
    ds = DeviceDataset(x, y, 32, seed=0)              # all-default knobs
    assert ds.dequant == spec and ds.dequant_impl == "affine"
    g = make_device_gather(32, ds.steps_per_epoch, num_slots=ds.num_slots)
    jaxpr = jax.make_jaxpr(g)(jnp.asarray(0, jnp.int32),
                              jax.random.PRNGKey(0), ds.peek())
    assert _gather_eqns(jaxpr) == []


def test_256_gather_detector_positive_control():
    """dequant_impl='lut' (the demoted round-4 diagnostic) MUST trip the
    detector — otherwise the test above could pass because the detector
    rotted, not because the lowering is right."""
    x, y = _data()
    ds = DeviceDataset(x, y, 32, seed=0, dequant_impl="lut")
    g = make_device_gather(32, ds.steps_per_epoch, num_slots=ds.num_slots,
                           dequant_impl="lut")
    jaxpr = jax.make_jaxpr(g)(jnp.asarray(0, jnp.int32),
                              jax.random.PRNGKey(0), ds.peek())
    assert _gather_eqns(jaxpr), "lut impl shows no 256-gather: detector rot"


def test_full_train_step_default_has_no_256_gather():
    """Same check one level up, on the whole jitted train step the bench
    actually times (the gather could hide behind factory plumbing)."""
    x, y = _data()
    mesh = make_mesh()
    ds = DeviceDataset(x, y, 32, mesh=mesh, seed=0, steps_per_next=2)
    state = TrainState.create_sharded(build_model("softmax"),
                                      optax.sgd(0.1), (32, 28, 28, 1), 0,
                                      replicated_sharding(mesh))
    step = make_indexed_train_step(32, ds.steps_per_epoch, mesh=mesh,
                                   unroll_steps=2, num_slots=ds.num_slots)
    with mesh:
        jaxpr = jax.make_jaxpr(lambda s, d: step(s, d))(state, ds.peek())
    assert _gather_eqns(jaxpr) == []


# ---- 4. fused kernels: bitwise parity with their unfused forms ----------

@pytest.mark.parametrize("spec,shape", [("unit", (28, 28, 1)),
                                        ("cifar", (32, 32, 3))])
def test_pallas_fused_gather_dequant_parity(spec, shape):
    """The Pallas kernel (interpret mode on CPU — the same kernel code a
    TPU compiles) == take-then-affine, bitwise, repeated indices
    included."""
    from distributedtensorflowexample_tpu.ops.pallas import (
        fused_gather_dequant)

    rng = np.random.RandomState(3)
    imgs = rng.randint(0, 256, (40,) + shape, dtype=np.uint8)
    idx = np.array([7, 0, 39, 7, 21, 3, 3, 12], np.int32)   # dups on purpose
    s, b = make_dequant_affine(spec)
    out = fused_gather_dequant(jnp.asarray(imgs), jnp.asarray(idx),
                               jnp.asarray(s), jnp.asarray(b))
    ref = jax.jit(apply_dequant_affine)(jnp.asarray(imgs[idx]),
                                        jnp.asarray(s), jnp.asarray(b))
    _bitwise_equal(out, ref)


def test_pallas_gather_path_matches_affine_gather():
    """dequant_impl='pallas' through make_device_gather == the unfused
    affine gather, bitwise, labels included."""
    x, y = _data()
    outs = {}
    for impl in ("affine", "pallas"):
        ds = DeviceDataset(x, y, 32, seed=4, dequant_impl=impl)
        g = make_device_gather(32, ds.steps_per_epoch,
                               num_slots=ds.num_slots, dequant_impl=impl)
        outs[impl] = jax.jit(g)(jnp.asarray(1, jnp.int32),
                                jax.random.PRNGKey(2), ds.peek())
    _bitwise_equal(outs["affine"]["image"], outs["pallas"]["image"])
    np.testing.assert_array_equal(np.asarray(outs["affine"]["label"]),
                                  np.asarray(outs["pallas"]["label"]))


def test_pallas_rejects_sharded_and_validates():
    x, y = _data(512)
    mesh = make_mesh()
    with pytest.raises(ValueError, match="replicated"):
        make_device_gather(64, 8, mesh=mesh, num_slots=3,
                           data_sharding="sharded", dequant_impl="pallas")
    with pytest.raises(ValueError, match="dequant_impl"):
        make_device_gather(64, 8, num_slots=3, dequant_impl="bogus")
    with pytest.raises(ValueError, match="dequant_impl"):
        DeviceDataset(x, y, 64, dequant_impl="bogus")


def test_fused_augment_dequant_matches_unfused():
    """cifar_augment_dequant_device (the augment-path input fix) ==
    augment then affine, and == augment then one-hot LUT — the same
    crops/flips, the same bits."""
    from distributedtensorflowexample_tpu.data.augment_device import (
        cifar_augment_dequant_device, cifar_augment_device)
    from distributedtensorflowexample_tpu.data.device_dataset import (
        apply_dequant_lut)

    u8 = np.random.RandomState(1).randint(0, 256, (16, 32, 32, 3),
                                          dtype=np.uint8)
    s, b = make_dequant_affine("cifar")
    lut = make_dequant_lut("cifar")
    key = jax.random.PRNGKey(9)
    fused = jax.jit(lambda u: cifar_augment_dequant_device(
        u, key, jnp.asarray(s), jnp.asarray(b)))(jnp.asarray(u8))
    aug = jax.jit(lambda u: cifar_augment_device(u, key))(jnp.asarray(u8))
    unfused_affine = jax.jit(apply_dequant_affine)(
        aug, jnp.asarray(s), jnp.asarray(b))
    unfused_onehot = jax.jit(apply_dequant_lut)(aug, jnp.asarray(lut))
    _bitwise_equal(fused, unfused_affine)
    _bitwise_equal(fused, unfused_onehot)
    with pytest.raises(TypeError, match="uint8"):
        cifar_augment_dequant_device(jnp.zeros((2, 32, 32, 3), jnp.float32),
                                     key, jnp.asarray(s), jnp.asarray(b))


def test_augmented_gather_parity_affine_vs_onehot():
    """End to end through make_device_gather with augment='cifar': the
    fused augment+dequant (affine family) and the augment-then-onehot
    path draw the same crops and emit the same bits."""
    x, y = _data(128, shape=(32, 32, 3))
    xn = _cifar_normalized(x)
    outs = {}
    for impl in ("affine", "onehot"):
        ds = DeviceDataset(xn, y, 32, seed=7, dequant_impl=impl)
        assert ds.dequant == "cifar"
        g = make_device_gather(32, ds.steps_per_epoch, augment="cifar",
                               num_slots=ds.num_slots, dequant_impl=impl)
        outs[impl] = jax.jit(g)(jnp.asarray(0, jnp.int32),
                                jax.random.PRNGKey(5), ds.peek())
    _bitwise_equal(outs["affine"]["image"], outs["onehot"]["image"])


# ---- prefetch / ring sizing (the input-dispatch overlap) ----------------

def test_ring_slots_cover_two_consecutive_windows():
    """ring_slots_for sizes for TWO windows (prefetch computes window
    N+1's permutations while window N is in flight) plus margin."""
    for window, spe in ((1, 10), (10, 10), (25, 10), (4, 100)):
        slots = DeviceDataset.ring_slots_for(window, spe)
        # Epochs two consecutive windows can touch, worst case:
        worst = -(-2 * window // spe) + 1
        assert slots >= worst, (window, spe, slots, worst)


def test_prefetch_is_pure_overlap():
    """prefetch() after each next() (what TrainLoop does post-dispatch)
    changes NOTHING a step can observe: for every window, the perm rows
    of every epoch that window reads are identical to a consumer that
    never prefetches.  (The FULL ring legitimately differs — prefetch's
    whole point is writing future epochs' slots early — so the check is
    on the slots the in-flight window gathers from, which is all the
    jitted gather ever dereferences.)"""
    x, y = _data(128)
    spn = 2
    a = DeviceDataset(x, y, 32, seed=11, steps_per_next=spn)
    b = DeviceDataset(x, y, 32, seed=11, steps_per_next=spn)
    spe = a.steps_per_epoch
    step = 0
    for _ in range(2 * spe):                     # cross several epochs
        da, db = next(a), next(b)
        # Materialize BEFORE prefetch(): the ring-row update donates the
        # old perm buffer (by design — the real consumer is the already-
        # enqueued step, stream-ordered before the overwrite), so the
        # yielded pytree's host handle dies once prefetch dispatches.
        pa, pb = np.asarray(da["perm"]), np.asarray(db["perm"])
        b.prefetch()
        for epoch in range(step // spe, (step + spn - 1) // spe + 1):
            s = epoch % a.num_slots
            np.testing.assert_array_equal(pa[s], pb[s], err_msg=(
                f"step {step} epoch {epoch} slot {s}"))
        step += spn


def test_train_loop_calls_prefetch_hook():
    """TrainLoop drives batches.prefetch() right after each dispatch —
    the overlap only happens if the loop actually calls it."""
    from distributedtensorflowexample_tpu.training.loop import TrainLoop

    calls = []

    class Batches:
        def __next__(self):
            return {"n": len(calls)}

        def prefetch(self):
            calls.append(1)

    class State:
        step = 0

    loop = TrainLoop(lambda s, b: (s, {"loss": jnp.float32(0.0)}),
                     Batches(), num_steps=3)
    loop.run(State())
    assert len(calls) == 3


# ---- 5. bench-config attestation (ROADMAP host-fed dequant check) -------

def test_bench_async_and_host_fed_configs_attest_affine_under_auto(
        tmp_path, small_synthetic):
    """Under --dequant auto NO bench path may silently regress to a LUT
    form (the round-5 tax).  The async bench config's detail.dequant line
    is ds.dequant_impl of the dataset bench._make builds — assert it
    resolves affine end-to-end through the real bench factory; the
    host-fed path resolves through dequant_host_batch's rule — assert the
    same AND that the jitted host-fed step contains no 256-gather."""
    import bench
    from distributedtensorflowexample_tpu.data.pipeline import Batcher

    # Async config (config 2), built exactly as bench.main does (sync=
    # False), on a 1-device mesh; data_dir points at an empty tmp dir so
    # the loader takes the deterministic synthetic fallback.
    mesh = make_mesh(1)
    with mesh:
        _, ds, _, _ = bench._make("mnist_cnn", "mnist", 32, 1, mesh,
                                  sync=False, data_dir=str(tmp_path),
                                  dequant_impl="auto")
    assert ds.dequant_impl == "affine", (
        f"async bench config resolved {ds.dequant_impl!r} under auto — "
        "detail.dequant would attest a LUT-family regression")

    # Host-fed: the Batcher quantizes the split and carries the spec; the
    # in-step dequant resolves through the SAME rule (dequant_host_batch).
    x, y = _data(64)
    batcher = Batcher(np.asarray(x), np.asarray(y), 32, quantize="auto")
    assert batcher.dequant is not None
    assert resolve_dequant_impl(batcher.dequant, "auto", "auto") == "affine"
    step = make_train_step(dequant=batcher.dequant)       # auto default
    state = TrainState.create(build_model("softmax"), optax.sgd(0.1),
                              np.zeros((32, 28, 28, 1), np.float32))
    batch = next(iter(batcher))
    assert batch["image"].dtype == np.uint8               # quantized feed
    jaxpr = jax.make_jaxpr(lambda s, b: step(s, b))(
        state, {"image": jnp.asarray(batch["image"]),
                "label": jnp.asarray(batch["label"])})
    assert _gather_eqns(jaxpr) == [], (
        "host-fed auto path traces a 256-entry table gather")
