"""ZeRO-3 / FSDP param+grad sharding (parallel/zero3.py, PR 12).

The module units (layout plan, row round-trip, overlap-knob bitwise
invariance), the trainer surface (--shard_params end-to-end with eval +
checkpoint/resume on the zero3_rows layout, refusals by name), and the
residency instrument (utils/profiling.state_residency_per_device — the
measured form of the 1/D claim).  The collective goldens and the
parity-vs-GSPMD gates live with their families in
tests/test_collectives.py and tests/test_lm.py.
"""

import os

import jax
import numpy as np
import optax
import pytest

from distributedtensorflowexample_tpu.config import RunConfig
from distributedtensorflowexample_tpu.data import DeviceDataset
from distributedtensorflowexample_tpu.data.synthetic import make_synthetic
from distributedtensorflowexample_tpu.models import build_model
from distributedtensorflowexample_tpu.parallel import (
    make_mesh, replicated_sharding)
from distributedtensorflowexample_tpu.parallel.bucketing import (
    DEFAULT_BUCKET_BYTES, bucket_padding_bytes, init_bucketed_opt_state)
from distributedtensorflowexample_tpu.parallel.sync import (
    make_indexed_train_step)
from distributedtensorflowexample_tpu.parallel.zero3 import Zero3Layout
from distributedtensorflowexample_tpu.training.state import TrainState
from distributedtensorflowexample_tpu.utils.profiling import (
    state_residency_per_device)

pytestmark = pytest.mark.collectives


def _tx():
    return optax.sgd(0.1, momentum=0.9)


def _state(model="softmax", b=64, shape=(28, 28, 1)):
    return TrainState.create_sharded(build_model(model), _tx(),
                                     (b,) + shape, 0,
                                     replicated_sharding(make_mesh()))


def _zero3_state(state, layout, mesh, bucket_bytes=DEFAULT_BUCKET_BYTES):
    return state.replace(
        opt_state=init_bucketed_opt_state(_tx(), state.params,
                                          bucket_bytes, mesh),
        params=layout.init_rows(state.params))


# ---- layout units -------------------------------------------------------

def test_layout_row_round_trip_and_residency():
    """init_rows -> materialize is bitwise the identity; every row is
    1/D per device; the padded totals match bucket_padding_bytes — the
    PR 6 accounting, reused verbatim."""
    mesh = make_mesh()
    D = mesh.size
    state = _state()
    leaves = jax.tree.leaves(state.params)
    n_elems = sum(l.size for l in leaves)
    pad = bucket_padding_bytes(leaves, D)
    layout = Zero3Layout(state.params, DEFAULT_BUCKET_BYTES, mesh)
    rows = layout.init_rows(jax.tree.map(lambda a: a + 0, state.params))
    assert isinstance(rows, tuple) and len(rows) == layout.num_buckets
    assert sum(r.size for r in rows) * 4 == n_elems * 4 + pad
    assert layout.padding_bytes == pad
    for r in rows:
        assert not r.sharding.is_fully_replicated
        assert r.addressable_shards[0].data.size == r.size // D
    full = layout.materialize(rows)
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(
        np.asarray(a), np.asarray(b)), state.params, full)


def test_layout_refuses_single_device():
    import types
    with pytest.raises(ValueError, match="multi-device"):
        Zero3Layout({"w": np.zeros(4, np.float32)}, 1 << 20,
                    types.SimpleNamespace(shape={"data": 1}))


def test_overlap_knob_is_bitwise_scheduling_only():
    """overlap on (double-buffered prefetch) vs off (serial gathers):
    identical params and metrics after fused multi-step calls — the
    knob moves issue order, never math.  Small buckets force a real
    multi-bucket chain so the _tie edges actually exist."""
    mesh = make_mesh()
    x, y = make_synthetic(512, (28, 28, 1), 10, seed=0)
    bb = 16 << 10           # split the CNN tree into several buckets
    outs = []
    for overlap in (True, False):
        state = _state("mnist_cnn")
        layout = Zero3Layout(state.params, bb, mesh)
        assert layout.num_buckets >= 3
        s_z = _zero3_state(state, layout, mesh, bb)
        ds = DeviceDataset(x, y, 64, mesh=mesh, seed=2, steps_per_next=2)
        step = make_indexed_train_step(
            64, ds.steps_per_epoch, mesh=mesh, num_slots=ds.num_slots,
            unroll_steps=2, zero3_layout=layout, zero3_overlap=overlap)
        with mesh:
            s_z, m = step(s_z, next(ds))
        outs.append((jax.tree.leaves(s_z.params), float(m["loss"])))
    (p_on, l_on), (p_off, l_off) = outs
    assert l_on == l_off
    for a, b in zip(p_on, p_off):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_step_refuses_unconverted_state_and_bn():
    """Trace-time refusals by name: params still a tree (the state was
    never converted to rows), and BatchNorm models (the bucketing.py
    argument verbatim)."""
    from distributedtensorflowexample_tpu.parallel.zero3 import (
        build_zero3_step_fn)
    mesh = make_mesh()
    state = _state()
    layout = Zero3Layout(state.params, DEFAULT_BUCKET_BYTES, mesh)
    fn = build_zero3_step_fn(0.0, "xla", mesh, mesh.size, 0, layout)
    with pytest.raises(ValueError, match="row layout"):
        fn(state, {"image": None, "label": None})
    import types
    fake = types.SimpleNamespace(batch_stats={"bn": 1})
    with pytest.raises(ValueError, match="BatchNorm"):
        fn(fake, {"image": None, "label": None})


def test_state_residency_instrument():
    """state_residency_per_device reads the live donated-argument
    shardings: replicated state measures full-size, the zero3 rows
    measure 1/D (+ reported padding) for params AND opt moments."""
    mesh = make_mesh()
    D = mesh.size
    state = _state()
    repl = state_residency_per_device(state)
    leaves = jax.tree.leaves(state.params)
    n_bytes = sum(l.size * 4 for l in leaves)
    assert repl["params_bytes_per_device"] == n_bytes
    assert repl["opt_state_bytes_per_device"] == n_bytes  # sgd momentum
    layout = Zero3Layout(state.params, DEFAULT_BUCKET_BYTES, mesh)
    s_z = _zero3_state(state, layout, mesh)
    rows = state_residency_per_device(s_z)
    padded = n_bytes + layout.padding_bytes
    assert rows["params_bytes_per_device"] == padded // D
    assert rows["opt_state_bytes_per_device"] == padded // D
    assert rows["state_bytes_per_device"] == 2 * (padded // D)


# ---- trainer surface ----------------------------------------------------

def test_trainer_shard_params_end_to_end_with_resume(tmp_path):
    """run_training --shard_params: trains, evals (the row state
    gathered once per eval), checkpoints the zero3_rows layout, and a
    resumed run restores INTO the row template and continues to the
    target step.  The cross-layout refusal fires by name when the same
    directory is reopened without the knob."""
    from distributedtensorflowexample_tpu.trainers.common import (
        run_training)
    log = str(tmp_path / "z3")
    kw = dict(dataset="synthetic", data_dir="/nonexistent", log_dir=log,
              batch_size=16, learning_rate=0.05, momentum=0.9,
              bucket_grads="auto", shard_params=True, dropout=0.0,
              checkpoint_every=4, log_every=4, steps_per_loop=1)
    summary = run_training(RunConfig(train_steps=8, **kw),
                           "softmax", "mnist")
    assert summary["steps"] == 8
    assert np.isfinite(summary["final_accuracy"])
    summary2 = run_training(RunConfig(train_steps=12, **kw),
                            "softmax", "mnist")
    assert summary2["steps"] == 12
    # Cross-layout resume refused by name (tree run into a zero3 dir).
    with pytest.raises(ValueError, match="zero3_rows"):
        run_training(RunConfig(train_steps=16, **dict(
            kw, shard_params=False, bucket_grads="")), "softmax", "mnist")


def test_trainer_refusals_by_name():
    from distributedtensorflowexample_tpu.trainers.common import (
        run_training)
    cfg = RunConfig(sync_mode="async", shard_params=True,
                    bucket_grads="auto", dataset="synthetic")
    with pytest.raises(ValueError, match="shard_params"):
        run_training(cfg, "softmax", "mnist")
    cfg = RunConfig(shard_params=True, dataset="synthetic")
    with pytest.raises(ValueError, match="bucket_grads"):
        run_training(cfg, "softmax", "mnist")


def test_flag_wiring():
    from distributedtensorflowexample_tpu.config import parse_flags
    cfg = parse_flags(["--shard_params", "true", "--bucket_grads", "auto",
                       "--zero3_overlap", "false"])
    assert cfg.shard_params is True
    assert cfg.zero3_overlap is False
    assert parse_flags([]).shard_params is False
    assert parse_flags([]).zero3_overlap is True
