"""Subprocess isolation + abort-only retry for the device-heavy files.

See tests/isolation_list.py for the why (XLA:CPU collective rendezvous
deadlock under host contention aborts the whole process).  Each isolated
file runs as its own pytest subprocess:

- ordinary test FAILURES propagate immediately (rc=1: no retry — a red
  test must stay red);
- an ABORT (SIGABRT/SIGSEGV: the deadlock signature) retries up to
  MAX_ATTEMPTS, because the deadlock is a property of the 1-core CI
  host's scheduler, not of the code under test (the terminate timeout in
  conftest bounds each hang to ~5 min); retries run at 4 virtual devices
  instead of 8 (DISTTF_TEST_DEVICES) — the identical mesh/psum/sharding
  code path with a narrower rendezvous, which under sustained load is
  the difference between repeated deadlock and a clean pass;
- the inner run's tail is always attached to the assertion message, so a
  real failure reads exactly like it would inline.
"""

import os
import re
import subprocess
import sys

import pytest

from isolation_list import ISOLATED_FILES

MAX_ATTEMPTS = 3
_ABORT_RCS = {-6, 134, -11, 139}     # SIGABRT / SIGSEGV, shell or raw


@pytest.mark.parametrize("fname", ISOLATED_FILES)
def test_isolated_file(fname):
    path = os.path.join(os.path.dirname(__file__), fname)
    assert os.path.exists(path), f"isolation list names missing file {fname}"
    env = dict(os.environ)
    env["DISTTF_INNER_PYTEST"] = "1"
    attempts = []
    for attempt in range(1, MAX_ATTEMPTS + 1):
        if attempt > 1:
            env["DISTTF_TEST_DEVICES"] = "4"   # narrower rendezvous
        # No explicit -q: pyproject addopts already has -q, and doubling
        # it (-qq) suppresses the "N passed" summary this wrapper parses.
        try:
            r = subprocess.run(
                [sys.executable, "-m", "pytest", path, "--no-header"],
                env=env, cwd=os.path.dirname(os.path.dirname(__file__)),
                capture_output=True, text=True, timeout=4500)
        except subprocess.TimeoutExpired:
            # Genuine slowness, not deadlock (a deadlock aborts at the
            # 300 s rendezvous terminate timeout); no retry.  No inner
            # output is available here — TimeoutExpired.stdout is None
            # under capture_output on this platform.
            attempts.append(f"attempt {attempt}: timeout 4500s")
            pytest.fail(f"{fname} exceeded 4500s; rerun it inline with "
                        f"DISTTF_INNER_PYTEST=1 to see where it hangs "
                        f"({'; '.join(attempts)})")
        tail = "\n".join((r.stdout + r.stderr).splitlines()[-15:])
        attempts.append(f"attempt {attempt}: rc={r.returncode}")
        if r.returncode == 0:
            m = re.search(r"(\d+) passed", r.stdout)
            assert m and int(m.group(1)) > 0, \
                f"{fname}: rc=0 but no tests ran\n{tail}"
            if attempt > 1:
                print(f"{fname}: recovered after abort retry at 4 virtual "
                      f"devices ({'; '.join(attempts)})")
            return
        if r.returncode not in _ABORT_RCS:
            pytest.fail(f"{fname} FAILED (rc={r.returncode}, no retry — "
                        f"not an abort)\n{tail}")
    pytest.fail(f"{fname} aborted {MAX_ATTEMPTS}x "
                f"({'; '.join(attempts)})\n{tail}")
