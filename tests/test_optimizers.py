"""LR-schedule and optimizer construction (training/optimizers.py).

The step schedule's drops are advertised at 50%/75% of --train_steps in
GLOBAL steps; with warmup the piecewise schedule is evaluated at
(step - warmup_steps), so the boundary arithmetic re-frames them — these
tests pin that the drops land where the docstring says.
"""

import numpy as np
import optax
import pytest

from distributedtensorflowexample_tpu.config import RunConfig
from distributedtensorflowexample_tpu.training.optimizers import (
    build_optimizer, build_schedule)


def _lr(sched, step: int) -> float:
    return float(sched(np.int32(step)))


def test_constant_schedule():
    sched = build_schedule(RunConfig(learning_rate=0.3,
                                     lr_schedule="constant",
                                     train_steps=100))
    assert _lr(sched, 0) == _lr(sched, 99) == pytest.approx(0.3)


def test_cosine_decays_to_zero():
    sched = build_schedule(RunConfig(learning_rate=0.2, lr_schedule="cosine",
                                     train_steps=100))
    assert _lr(sched, 0) == pytest.approx(0.2)
    assert _lr(sched, 50) == pytest.approx(0.1, rel=1e-3)
    assert _lr(sched, 100) == pytest.approx(0.0, abs=1e-6)


def test_step_schedule_drops_at_advertised_global_steps():
    sched = build_schedule(RunConfig(learning_rate=0.1, lr_schedule="step",
                                     train_steps=100))
    assert _lr(sched, 49) == pytest.approx(0.1)
    assert _lr(sched, 50) == pytest.approx(0.01)
    assert _lr(sched, 74) == pytest.approx(0.01)
    assert _lr(sched, 75) == pytest.approx(0.001)


def test_step_schedule_with_warmup_keeps_global_drop_points():
    """Warmup shifts the schedule's evaluation frame; the /10 drops must
    still land at 50% and 75% of train_steps in GLOBAL steps."""
    sched = build_schedule(RunConfig(learning_rate=0.1, lr_schedule="step",
                                     train_steps=100, warmup_steps=10))
    assert _lr(sched, 0) == pytest.approx(0.0)          # warmup start
    assert _lr(sched, 5) == pytest.approx(0.05)         # linear ramp
    assert _lr(sched, 10) == pytest.approx(0.1)         # ramp done
    assert _lr(sched, 49) == pytest.approx(0.1)
    assert _lr(sched, 50) == pytest.approx(0.01)        # global 50%
    assert _lr(sched, 75) == pytest.approx(0.001)       # global 75%


def test_unknown_schedule_rejected():
    with pytest.raises(ValueError, match="unknown lr_schedule"):
        build_schedule(RunConfig(lr_schedule="nope", train_steps=10))


def test_weight_decay_chains_decay_before_sgd():
    """weight_decay > 0 adds decoupled decay: the update for zero
    gradients is -lr * wd * param."""
    import jax.numpy as jnp

    tx = build_optimizer(RunConfig(learning_rate=0.1, momentum=0.0,
                                   weight_decay=0.01, train_steps=10))
    params = {"w": jnp.ones((4,))}
    state = tx.init(params)
    updates, _ = tx.update({"w": jnp.zeros((4,))}, state, params)
    np.testing.assert_allclose(np.asarray(updates["w"]),
                               -0.1 * 0.01 * np.ones(4), rtol=1e-5)


def test_momentum_sgd_matches_optax_reference():
    import jax.numpy as jnp

    tx = build_optimizer(RunConfig(learning_rate=0.1, momentum=0.9,
                                   train_steps=10))
    ref = optax.sgd(0.1, momentum=0.9)
    params = {"w": jnp.ones((3,))}
    grads = {"w": jnp.full((3,), 0.5)}
    s1, s2 = tx.init(params), ref.init(params)
    for _ in range(3):
        u1, s1 = tx.update(grads, s1, params)
        u2, s2 = ref.update(grads, s2, params)
    np.testing.assert_allclose(np.asarray(u1["w"]), np.asarray(u2["w"]),
                               rtol=1e-6)
