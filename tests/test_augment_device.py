"""On-device CIFAR augmentation (data/augment_device.py) and its wiring
into the device-resident CIFAR training path."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributedtensorflowexample_tpu.data.augment_device import (
    cifar_augment_device)


def _images(b=8, h=32, w=32, c=3, seed=0):
    rng = np.random.RandomState(seed)
    return jnp.asarray(rng.randn(b, h, w, c).astype(np.float32))


def test_every_output_is_a_valid_crop_or_flip():
    """Each augmented image must equal one of the 81 crops (x2 flips) of
    its reflect-padded source — exhaustively checked."""
    images = _images(b=6)
    out = np.asarray(cifar_augment_device(images, jax.random.PRNGKey(0)))
    padded = np.pad(np.asarray(images), ((0, 0), (4, 4), (4, 4), (0, 0)),
                    mode="reflect")
    for i in range(images.shape[0]):
        found = False
        for y0 in range(9):
            for x0 in range(9):
                crop = padded[i, y0:y0 + 32, x0:x0 + 32]
                if (np.array_equal(out[i], crop)
                        or np.array_equal(out[i], crop[:, ::-1])):
                    found = True
                    break
            if found:
                break
        assert found, f"image {i} is not any crop/flip of its source"


def test_augment_deterministic_per_key():
    images = _images()
    k = jax.random.PRNGKey(7)
    np.testing.assert_array_equal(cifar_augment_device(images, k),
                                  cifar_augment_device(images, k))
    assert not np.array_equal(cifar_augment_device(images, k),
                              cifar_augment_device(images,
                                                   jax.random.PRNGKey(8)))


def test_augment_varies_within_batch():
    """With 32 images the odds of all draws being the identity are nil —
    the batch must not pass through unchanged."""
    images = _images(b=32)
    out = cifar_augment_device(images, jax.random.PRNGKey(1))
    assert not np.array_equal(out, images)


def test_device_resident_cifar_training(tmp_path, monkeypatch):
    """run_training on CIFAR with augmentation stays on the device-resident
    path (auto) and trains end-to-end, including multi-step fusion."""
    from distributedtensorflowexample_tpu.config import RunConfig
    from distributedtensorflowexample_tpu.data import cifar10
    from distributedtensorflowexample_tpu.trainers.common import run_training

    monkeypatch.setattr(cifar10, "_SYNTH_SIZES",
                        {"train": 1024, "test": 256})
    cfg = RunConfig(train_steps=8, steps_per_loop=4, batch_size=64,
                    global_batch=True, learning_rate=0.05, momentum=0.9,
                    dataset="synthetic", data_dir=str(tmp_path),
                    log_dir=str(tmp_path / "logs"), resume=False,
                    log_every=4)
    out = run_training(cfg, "resnet20", "cifar10", augment=True)
    assert out["steps"] == 8
    assert np.isfinite(out["final_accuracy"])
