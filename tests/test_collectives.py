"""Collective accounting + bucketed gradient collectives (PR 6).

The comms twin of tests/test_bytes.py: the HLO collective inventory
(utils/profiling.collective_inventory) is gated against the bytes audit's
own "collective" category (same text, same weights — exact), the golden
per-trainer multisets generalize test_device_data.py's collective-set
assertion into pinned measurements, and the ``--bucket_grads`` schedules
are parity-gated (bitwise where the program permits — softmax, both
modes — and the shard_update allclose standard for conv models, same
reason: summation order, not math).

Inline and tier-1-safe: single-digit fused dispatches per test, no full
training loops.
"""

import os
import subprocess
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import bench_collectives
from distributedtensorflowexample_tpu.data import DeviceDataset
from distributedtensorflowexample_tpu.data.synthetic import make_synthetic
from distributedtensorflowexample_tpu.models import build_model
from distributedtensorflowexample_tpu.parallel import (
    make_mesh, replicated_sharding)
from distributedtensorflowexample_tpu.parallel.bucketing import (
    DEFAULT_BUCKET_BYTES, bucket_padding_bytes, init_bucketed_opt_state,
    plan_buckets, resolve_bucket_bytes)
from distributedtensorflowexample_tpu.parallel.sync import (
    make_indexed_train_step)
from distributedtensorflowexample_tpu.training.state import TrainState
from distributedtensorflowexample_tpu.utils.profiling import (
    bytes_audit, collective_inventory, collective_inventory_of)

pytestmark = pytest.mark.collectives

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _data(n=512, shape=(28, 28, 1)):
    return make_synthetic(n, shape, 10, seed=0)


def _state(model, tx, b=64, shape=(28, 28, 1)):
    return TrainState.create_sharded(model, tx, (b,) + shape, 0,
                                     replicated_sharding(make_mesh()))


# ---- the parser ---------------------------------------------------------

_HLO = """
ENTRY %main (p0: f32[8]) -> f32[8] {
  %p0 = f32[8]{0} parameter(0)
  %ar = f32[8]{0} all-reduce(f32[8]{0} %p0), replica_groups={{0,1,2,3,4,5,6,7}}, to_apply=%add
  %ars = f32[8]{0} all-reduce-start(f32[8]{0} %ar), replica_groups={{0,1},{2,3}}
  %ard = f32[8]{0} all-reduce-done(f32[8]{0} %ars)
  %w = f32[8]{0} while(f32[8]{0} %ard), condition=%cond, body=%body
  ROOT %t = f32[8]{0} add(f32[8]{0} %w, f32[8]{0} %ar)
}
%body (p: f32[8]) -> f32[8] {
  %p = f32[8]{0} parameter(0)
  %rs = f32[1]{0} reduce-scatter(f32[8]{0} %p), replica_groups=[1,8]<=[8], dimensions={0}
  %ag = f32[8]{0} all-gather(f32[1]{0} %rs), dimensions={0}
  ROOT %r = f32[8]{0} add(f32[8]{0} %ag, f32[8]{0} %p)
}
%cond (p: f32[8]) -> pred[] {
  %p = f32[8]{0} parameter(0)
  ROOT %lt = pred[] constant(1)
}
"""


def test_collective_inventory_parsing():
    """Opcode normalization (-start counted once, -done skipped), operand
    vs output bytes, replica-group capture, and scan-body weighting."""
    inv = collective_inventory(_HLO, unroll=2)
    # entry: 2 all-reduces (plain + start/done pair), each weight 1 ->
    # 0.5/step at unroll 2; body: weight 2 -> 1/step.
    assert inv["multiset"] == {"all-reduce": 1, "all-gather": 1,
                               "reduce-scatter": 1}
    per = inv["per_step"]
    assert per["all-reduce"]["out_bytes"] == 32          # 2 x 32 B / 2
    assert per["reduce-scatter"] == {"count": 1, "out_bytes": 4,
                                     "accounting_bytes": 4 + 32}
    assert per["all-gather"] == {"count": 1, "out_bytes": 32,
                                 "accounting_bytes": 32 + 4}
    groups = {r["name"]: r["replica_groups"] for r in inv["ops"]}
    assert groups["ar"] == "{{0,1,2,3,4,5,6,7}}"
    assert groups["ars"] == "{{0,1},{2,3}}"
    assert groups["rs"] == "[1,8]<=[8]"
    assert not any(r["name"] == "ard" for r in inv["ops"])
    assert collective_inventory("")["multiset"] == {}


def test_inventory_ties_out_against_bytes_audit_and_cost():
    """The acceptance gate: the inventory's accounting bytes EQUAL the
    bytes audit's "collective" category (the HLO-metadata tie-out is
    exact — same parse, same out+operands convention), and the audit
    total tracks XLA's cost_analysis at the PR-2 standard (15% on
    small programs; agreement tightens with size, <0.1% at batch-256
    ResNet — see tests/test_bytes.py)."""
    mesh = make_mesh()
    x, y = _data()
    ds = DeviceDataset(x, y, 64, mesh=mesh, seed=0)
    state = _state(build_model("softmax"), optax.sgd(0.1, momentum=0.9))
    step = make_indexed_train_step(64, ds.steps_per_epoch, mesh=mesh,
                                   num_slots=ds.num_slots)
    with mesh:
        compiled = step.lower(state, ds.peek()).compile()
        hlo = compiled.as_text()
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0]
    inv = collective_inventory(hlo)
    audit = bytes_audit(hlo)
    assert inv["total_accounting_bytes_per_step"] == \
        audit["by_category_per_step"]["collective"]
    if "bytes accessed" in ca:       # backend-dependent key, like PR 2
        assert abs(audit["bytes_total"] - ca["bytes accessed"]) \
            <= 0.15 * ca["bytes accessed"]


# ---- golden per-trainer multisets (the generalized collective-set
# assertion: sync / shard_update / async each pin their inventory) ------

def test_sync_softmax_golden_inventory():
    """The sync data-parallel softmax step: 2 per-parameter gradient
    all-reduces (kernel 31360 B + bias 40 B) + 2 scalar metric
    all-reduces — 31408 B/step on the wire, at any unroll (scan bodies
    weight by trip count, so per-step accounting is unroll-invariant)."""
    mesh = make_mesh()
    x, y = _data()
    state = _state(build_model("softmax"), optax.sgd(0.1, momentum=0.9))
    ds1 = DeviceDataset(x, y, 64, mesh=mesh, seed=0)
    ds4 = DeviceDataset(x, y, 64, mesh=mesh, seed=0, steps_per_next=4)
    with mesh:
        one = make_indexed_train_step(64, ds1.steps_per_epoch, mesh=mesh,
                                      num_slots=ds1.num_slots)
        inv1 = collective_inventory_of(one, (state, ds1.peek()))
        fused = make_indexed_train_step(64, ds4.steps_per_epoch, mesh=mesh,
                                        num_slots=ds4.num_slots,
                                        unroll_steps=4)
        inv4 = collective_inventory_of(fused, (state, ds4.peek()), unroll=4)
    assert inv1["multiset"] == {"all-reduce": 4}
    assert inv1["total_out_bytes_per_step"] == 31408
    assert inv4["multiset"] == inv1["multiset"]
    assert inv4["total_out_bytes_per_step"] == \
        inv1["total_out_bytes_per_step"]


def test_shard_update_golden_inventory():
    """The GSPMD-constraint form of --shard_update on THIS backend: the
    partitioner keeps plain all-reduces (no reduce-scatter/all-gather
    decomposition on XLA:CPU) — the measured fact that motivates the
    explicit bucketed ZeRO-1 schedule, which is the configuration that
    actually emits the paper's reduce-scatter + all-gather (pinned in
    test_bucketed_zero1_golden_inventory)."""
    from distributedtensorflowexample_tpu.training.optimizers import (
        cross_replica_update_sharding, update_shardings)
    mesh = make_mesh()
    x, y = _data()
    ds = DeviceDataset(x, y, 64, mesh=mesh, seed=0)
    tx = cross_replica_update_sharding(optax.sgd(0.1, momentum=0.9), mesh)
    state = _state(build_model("softmax"), tx)
    state = state.replace(opt_state=jax.device_put(
        state.opt_state, update_shardings(state.opt_state, mesh)))
    step = make_indexed_train_step(64, ds.steps_per_epoch, mesh=mesh,
                                   num_slots=ds.num_slots)
    with mesh:
        inv = collective_inventory_of(step, (state, ds.peek()))
    assert inv["multiset"] == {"all-reduce": 4}
    assert inv["total_out_bytes_per_step"] == 31408


def test_async_golden_inventory_and_bucketed_average():
    """The async local-SGD step: per-leaf worker-average all-reduces
    (cond-gated on the period — counted at module weight; sustained
    bytes divide by the period) + the fused scalar metrics psum pair.
    --bucket_grads fuses the per-leaf average psums into one bucket."""
    from distributedtensorflowexample_tpu.parallel.async_ps import (
        make_indexed_async_train_step, make_worker_state)
    mesh = make_mesh()
    x, y = _data()
    ds = DeviceDataset(x, y, 64, mesh=mesh, seed=0)
    state = _state(build_model("softmax"), optax.sgd(0.1))
    state = make_worker_state(state, mesh.size, mesh)
    with mesh:
        plain = make_indexed_async_train_step(
            mesh.size, 8, 64, ds.steps_per_epoch, mesh=mesh,
            num_slots=ds.num_slots)
        inv = collective_inventory_of(plain, (state, ds.peek()))
        bucketed = make_indexed_async_train_step(
            mesh.size, 8, 64, ds.steps_per_epoch, mesh=mesh,
            num_slots=ds.num_slots, bucket_bytes=1 << 20)
        inv_b = collective_inventory_of(bucketed, (state, ds.peek()))
    assert inv["multiset"] == {"all-reduce": 4}     # w, b, loss, acc
    assert inv["total_out_bytes_per_step"] == 31408
    assert inv_b["multiset"] == {"all-reduce": 3}   # bucket, loss, acc
    assert inv_b["total_out_bytes_per_step"] == 31408


def test_async_bucketed_average_bitwise():
    """Bucketing the worker average is bitwise: same cross-device
    additions, regrouped into one psum."""
    from distributedtensorflowexample_tpu.parallel.async_ps import (
        make_indexed_async_train_step, make_worker_state)
    mesh = make_mesh()
    x, y = _data()
    mk = lambda: DeviceDataset(x, y, 64, mesh=mesh, seed=2,
                               steps_per_next=4)
    mk_state = lambda: make_worker_state(
        _state(build_model("softmax"), optax.sgd(0.1)), mesh.size, mesh)
    outs = []
    with mesh:
        for bb in (None, 1 << 20):
            ds = mk()
            state = mk_state()
            step = make_indexed_async_train_step(
                mesh.size, 4, 64, ds.steps_per_epoch, mesh=mesh,
                unroll_steps=4, num_slots=ds.num_slots, bucket_bytes=bb)
            state, m = step(state, next(ds))    # crosses the period
            outs.append((jax.tree.leaves(state.params),
                         float(m["loss"])))
    (p0, l0), (p1, l1) = outs
    assert l0 == l1
    for a, c in zip(p0, p1):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(c))


# ---- the bucketed schedules -------------------------------------------

def test_bucketed_golden_inventory_and_bitwise_parity():
    """--bucket_grads on softmax: strictly fewer all-reduce ops (4 -> 3:
    one gradient bucket + the metrics pair), unchanged total collective
    bytes, and BITWISE-identical params/loss/metrics vs the GSPMD
    default (batch_stats empty-by-construction on softmax, so the full
    remat-style parity triple holds bitwise)."""
    mesh = make_mesh()
    x, y = _data()
    mk = lambda: DeviceDataset(x, y, 64, mesh=mesh, seed=4)
    mk_state = lambda: _state(build_model("softmax"),
                              optax.sgd(0.1, momentum=0.9))
    ds = mk()
    ref = make_indexed_train_step(64, ds.steps_per_epoch, mesh=mesh,
                                  num_slots=ds.num_slots)
    bkt = make_indexed_train_step(64, ds.steps_per_epoch, mesh=mesh,
                                  num_slots=ds.num_slots,
                                  bucket_bytes=DEFAULT_BUCKET_BYTES)
    s_ref, s_bkt = mk_state(), mk_state()
    with mesh:
        inv_ref = collective_inventory_of(ref, (s_ref, ds.peek()))
        inv_bkt = collective_inventory_of(bkt, (s_bkt, ds.peek()))
        ds_r, ds_b = mk(), mk()
        for _ in range(3):
            s_ref, m_ref = ref(s_ref, next(ds_r))
            s_bkt, m_bkt = bkt(s_bkt, next(ds_b))
    assert inv_bkt["multiset"] == {"all-reduce": 3}
    assert inv_bkt["per_step"]["all-reduce"]["count"] < \
        inv_ref["per_step"]["all-reduce"]["count"]
    assert inv_bkt["total_out_bytes_per_step"] == \
        inv_ref["total_out_bytes_per_step"]
    assert float(m_ref["loss"]) == float(m_bkt["loss"])
    assert float(m_ref["accuracy"]) == float(m_bkt["accuracy"])
    assert s_bkt.batch_stats == s_ref.batch_stats
    jax.tree.map(lambda a, c: np.testing.assert_array_equal(a, c),
                 s_ref.params, s_bkt.params)


def test_bucketed_zero1_golden_inventory_and_bitwise_parity():
    """--bucket_grads + --shard_update: the explicit ZeRO-1 bucket
    schedule — per bucket ONE reduce-scatter (grad shard in), ONE
    all-gather (updated params out) — the first configuration whose
    compiled HLO actually carries arXiv:2004.13336's collective pair on
    this backend (the constraint form keeps plain all-reduces, pinned
    above).  Reduction bytes are conserved up to the reported row
    padding; softmax parity is bitwise including the metrics."""
    mesh = make_mesh()
    D = mesh.size
    x, y = _data()
    mk = lambda: DeviceDataset(x, y, 64, mesh=mesh, seed=4)
    mk_tx = lambda: optax.sgd(0.1, momentum=0.9)
    ds = mk()
    ref = make_indexed_train_step(64, ds.steps_per_epoch, mesh=mesh,
                                  num_slots=ds.num_slots)
    z1 = make_indexed_train_step(64, ds.steps_per_epoch, mesh=mesh,
                                 num_slots=ds.num_slots,
                                 bucket_bytes=DEFAULT_BUCKET_BYTES,
                                 bucket_shard_update=True)
    s_ref = _state(build_model("softmax"), mk_tx())
    s_z = _state(build_model("softmax"), mk_tx())
    s_z = s_z.replace(opt_state=init_bucketed_opt_state(
        mk_tx(), s_z.params, DEFAULT_BUCKET_BYTES, mesh))
    # ZeRO-1 state residency: every non-scalar optimizer leaf is a
    # bucket row — 1/D of the padded params per device, by construction.
    pleaves = jax.tree.leaves(s_ref.params)
    padded = sum(l.size for l in pleaves) * 4 + bucket_padding_bytes(
        pleaves, D)
    rows = [l for l in jax.tree.leaves(s_z.opt_state)
            if getattr(l, "ndim", 0)]
    assert sum(r.size for r in rows) * 4 == padded
    assert all(not r.sharding.is_fully_replicated for r in rows)
    with mesh:
        inv = collective_inventory_of(z1, (s_z, ds.peek()))
        ds_r, ds_z = mk(), mk()
        for _ in range(3):
            s_ref, m_ref = ref(s_ref, next(ds_r))
            s_z, m_z = z1(s_z, next(ds_z))
    assert inv["multiset"] == {"all-gather": 1, "all-reduce": 2,
                               "reduce-scatter": 1}
    per = inv["per_step"]
    assert per["reduce-scatter"]["out_bytes"] == padded // D
    assert per["all-gather"]["out_bytes"] == padded
    assert per["all-reduce"]["out_bytes"] == 8          # the metrics pair
    assert float(m_ref["loss"]) == float(m_z["loss"])
    jax.tree.map(lambda a, c: np.testing.assert_array_equal(a, c),
                 s_ref.params, s_z.params)


def test_zero3_golden_inventory_prefetch_order_and_bitwise_parity():
    """--shard_params on softmax (PR 12): the ZeRO-3 schedule — the
    whole tree fits ONE knee-sized bucket, so per step ONE param
    all-gather in the FORWARD (prefetch: it textually precedes the
    reduce-scatter in the compiled module, where ZeRO-1's
    update-closing AG follows its RS), ONE reduce-scatter placed by the
    gather's transpose in the backward, the fused metrics pair — and NO
    step-closing all-gather (the updated 1/D row writes straight back).
    Reduction bytes conserved up to the reported row padding; parity vs
    the GSPMD default is BITWISE including metrics (the ZeRO-1
    standard), and both params and opt state live as 1/D rows."""
    from distributedtensorflowexample_tpu.parallel.zero3 import Zero3Layout
    mesh = make_mesh()
    D = mesh.size
    x, y = _data()
    mk = lambda: DeviceDataset(x, y, 64, mesh=mesh, seed=4)
    mk_tx = lambda: optax.sgd(0.1, momentum=0.9)
    ds = mk()
    ref = make_indexed_train_step(64, ds.steps_per_epoch, mesh=mesh,
                                  num_slots=ds.num_slots)
    s_ref = _state(build_model("softmax"), mk_tx())
    s_z = _state(build_model("softmax"), mk_tx())
    pleaves = jax.tree.leaves(s_ref.params)
    padded = sum(l.size for l in pleaves) * 4 + bucket_padding_bytes(
        pleaves, D)
    layout = Zero3Layout(s_z.params, DEFAULT_BUCKET_BYTES, mesh)
    z3 = make_indexed_train_step(64, ds.steps_per_epoch, mesh=mesh,
                                 num_slots=ds.num_slots,
                                 zero3_layout=layout)
    s_z = s_z.replace(opt_state=init_bucketed_opt_state(
        mk_tx(), s_z.params, DEFAULT_BUCKET_BYTES, mesh))
    s_z = s_z.replace(params=layout.init_rows(s_z.params))
    # ZeRO-3 residency: params AND opt moments are 1/D rows.
    for leaf in list(s_z.params) + [l for l in jax.tree.leaves(
            s_z.opt_state) if getattr(l, "ndim", 0)]:
        assert not leaf.sharding.is_fully_replicated
    assert sum(r.size for r in s_z.params) * 4 == padded
    with mesh:
        compiled = z3.lower(s_z, ds.peek()).compile()
        inv = collective_inventory(compiled.as_text())
        ds_r, ds_z = mk(), mk()
        for _ in range(3):
            s_ref, m_ref = ref(s_ref, next(ds_r))
            s_z, m_z = z3(s_z, next(ds_z))
    assert inv["multiset"] == {"all-gather": 1, "all-reduce": 2,
                               "reduce-scatter": 1}
    per = inv["per_step"]
    assert per["all-gather"]["out_bytes"] == padded
    assert per["reduce-scatter"]["out_bytes"] == padded // D
    assert per["all-reduce"]["out_bytes"] == 8          # the metrics pair
    # The AG-prefetch pin: HLO prints computations in topological order,
    # and the zero3 module's param gather precedes the backward's RS —
    # ZeRO-1's module (pinned above) has the opposite order (its AG
    # closes the update).
    hlo = compiled.as_text()
    assert hlo.index("all-gather") < hlo.index("reduce-scatter")
    assert float(m_ref["loss"]) == float(m_z["loss"])
    assert float(m_ref["accuracy"]) == float(m_z["accuracy"])
    full = layout.materialize(s_z.params)
    jax.tree.map(lambda a, c: np.testing.assert_array_equal(
        np.asarray(a), np.asarray(c)), s_ref.params, full)


def test_zero3_lm_tiny_multi_bucket_golden_inventory():
    """The per-bucket schedule at lm_tiny: a sub-knee bucket cap splits
    the tree into several buckets — the compiled module carries exactly
    one AG + one RS PER BUCKET (the prefetch ladder bench_lm measures
    at lm_base), metrics on the fused pair, gradient reduction bytes
    conserved up to the row padding."""
    from distributedtensorflowexample_tpu.data.lm import load_lm
    from distributedtensorflowexample_tpu.parallel.zero3 import Zero3Layout
    mesh = make_mesh()
    D = mesh.size
    x, y = load_lm("", "train", num=128, seq_len=16, seed=0)
    mk_tx = lambda: optax.sgd(0.1, momentum=0.9)
    ds = DeviceDataset(x, y, 32, mesh=mesh, seed=0, token_data=True)
    state = TrainState.create_sharded(
        build_model("lm_tiny"), mk_tx(), (32, 16), 0,
        replicated_sharding(mesh))
    bb = 64 << 10
    layout = Zero3Layout(state.params, bb, mesh)
    assert layout.num_buckets >= 3       # a real multi-bucket ladder
    z3 = make_indexed_train_step(32, ds.steps_per_epoch, mesh=mesh,
                                 num_slots=ds.num_slots,
                                 zero3_layout=layout)
    s_z = state.replace(opt_state=init_bucketed_opt_state(
        mk_tx(), state.params, bb, mesh))
    s_z = s_z.replace(params=layout.init_rows(s_z.params))
    with mesh:
        inv = collective_inventory_of(z3, (s_z, ds.peek()))
    n = layout.num_buckets
    assert inv["multiset"] == {"all-gather": n, "all-reduce": 2,
                               "reduce-scatter": n}
    pleaves = layout.leaf_specs
    padded = sum(l.size * l.dtype.itemsize for l in pleaves) \
        + bucket_padding_bytes(pleaves, D)
    per = inv["per_step"]
    assert per["all-gather"]["out_bytes"] == padded
    assert per["reduce-scatter"]["out_bytes"] == padded // D


@pytest.mark.lm
def test_lm_golden_inventory():
    """The transformer-LM trainer's golden multisets (the third trainer
    family): 30 param leaves -> 30 per-parameter gradient all-reduces +
    the 2 metric scalars on the GSPMD default; ONE knee-sized bucket +
    the fused metrics pair under --bucket_grads (the whole lm_tiny tree
    fits one bucket); the explicit per-bucket RS+AG pair + metrics under
    the composed ZeRO-1 schedule.  BN-free by construction, so unlike
    resnet20 every schedule is legal for this model."""
    from distributedtensorflowexample_tpu.data.lm import load_lm
    from distributedtensorflowexample_tpu.parallel.bucketing import (
        init_bucketed_opt_state)
    mesh = make_mesh()
    x, y = load_lm("", "train", num=128, seq_len=16, seed=0)
    mk_tx = lambda: optax.sgd(0.1, momentum=0.9)
    ds = DeviceDataset(x, y, 32, mesh=mesh, seed=0, token_data=True)
    state = TrainState.create_sharded(
        build_model("lm_tiny"), mk_tx(), (32, 16), 0,
        replicated_sharding(mesh))
    plain = make_indexed_train_step(32, ds.steps_per_epoch, mesh=mesh,
                                    num_slots=ds.num_slots)
    bkt = make_indexed_train_step(32, ds.steps_per_epoch, mesh=mesh,
                                  num_slots=ds.num_slots,
                                  bucket_bytes=DEFAULT_BUCKET_BYTES)
    z1 = make_indexed_train_step(32, ds.steps_per_epoch, mesh=mesh,
                                 num_slots=ds.num_slots,
                                 bucket_bytes=DEFAULT_BUCKET_BYTES,
                                 bucket_shard_update=True)
    s_z = state.replace(opt_state=init_bucketed_opt_state(
        mk_tx(), state.params, DEFAULT_BUCKET_BYTES, mesh))
    with mesh:
        inv_p = collective_inventory_of(plain, (state, ds.peek()))
        inv_b = collective_inventory_of(bkt, (state, ds.peek()))
        inv_z = collective_inventory_of(z1, (s_z, ds.peek()))
    assert inv_p["multiset"] == {"all-reduce": 32}      # 30 grads + 2
    assert inv_b["multiset"] == {"all-reduce": 3}       # 1 bucket + 2
    assert inv_z["multiset"] == {"all-gather": 1, "all-reduce": 2,
                                 "reduce-scatter": 1}
    # Gradient bytes conserved by bucketing (metrics pair rides along).
    assert inv_b["total_out_bytes_per_step"] >= \
        inv_p["total_out_bytes_per_step"] - 16


def test_bucket_size_invariance_and_fewer_ops_on_cnn():
    """mnist_cnn (8 grad leaves -> 8 per-parameter all-reduces + 2
    metric scalars on the default path): bucketing is bitwise ACROSS
    bucket sizes (the knob's own invariance — same additions,
    regrouped), strictly fewer all-reduces at unchanged total bytes,
    and matches the GSPMD default to the shard_update allclose standard
    (the shard_map backward fuses differently on this backend; the
    deviation is reduction order, not math)."""
    mesh = make_mesh()
    x, y = _data()
    mk = lambda: DeviceDataset(x, y, 64, mesh=mesh, seed=7)
    model = build_model("mnist_cnn", dropout=0.0)
    mk_state = lambda: _state(model, optax.sgd(0.1, momentum=0.9))
    ds = mk()
    ref = make_indexed_train_step(64, ds.steps_per_epoch, mesh=mesh,
                                  num_slots=ds.num_slots)
    big = make_indexed_train_step(64, ds.steps_per_epoch, mesh=mesh,
                                  num_slots=ds.num_slots,
                                  bucket_bytes=16 << 20)
    small = make_indexed_train_step(64, ds.steps_per_epoch, mesh=mesh,
                                    num_slots=ds.num_slots,
                                    bucket_bytes=64 << 10)
    s_ref, s_big, s_small = mk_state(), mk_state(), mk_state()
    with mesh:
        inv_ref = collective_inventory_of(ref, (s_ref, ds.peek()))
        inv_big = collective_inventory_of(big, (s_big, ds.peek()))
        ds_r, ds_b, ds_s = mk(), mk(), mk()
        for _ in range(2):
            s_ref, _ = ref(s_ref, next(ds_r))
            s_big, _ = big(s_big, next(ds_b))
            s_small, _ = small(s_small, next(ds_s))
    assert inv_ref["multiset"] == {"all-reduce": 10}
    assert inv_big["multiset"] == {"all-reduce": 3}
    assert inv_big["total_out_bytes_per_step"] == \
        inv_ref["total_out_bytes_per_step"]
    jax.tree.map(lambda a, c: np.testing.assert_array_equal(a, c),
                 s_big.params, s_small.params)       # bitwise across sizes
    # vs the GSPMD default: XLA:CPU fuses the conv backward differently
    # inside the shard_map region, seeding ~1e-4 reduction-order grad
    # deviations that two momentum steps amplify — same-math, different
    # order (measured against single-device ground truth: BOTH paths
    # deviate from it at the same magnitude).  The bitwise gates are the
    # cross-bucket-size identity above and the softmax tests.
    jax.tree.map(lambda a, c: np.testing.assert_allclose(
        np.asarray(a), np.asarray(c), rtol=2e-2, atol=1e-3),
        s_ref.params, s_big.params)


def test_bucketed_partial_aggregation_bitwise():
    """replicas_to_aggregate under bucketing: the rotating-subset row
    weights are computed in GLOBAL row coordinates inside the shard_map
    region — bitwise against the GSPMD form on softmax."""
    mesh = make_mesh()
    x, y = _data()
    mk = lambda: DeviceDataset(x, y, 64, mesh=mesh, seed=3)
    mk_state = lambda: _state(build_model("softmax"), optax.sgd(0.2))
    ds = mk()
    kw = dict(mesh=mesh, num_slots=ds.num_slots,
              num_replicas=mesh.size, replicas_to_aggregate=3)
    ref = make_indexed_train_step(64, ds.steps_per_epoch, **kw)
    bkt = make_indexed_train_step(64, ds.steps_per_epoch,
                                  bucket_bytes=1 << 20, **kw)
    s_ref, s_bkt = mk_state(), mk_state()
    with mesh:
        ds_r, ds_b = mk(), mk()
        for _ in range(3):
            s_ref, m_ref = ref(s_ref, next(ds_r))
            s_bkt, m_bkt = bkt(s_bkt, next(ds_b))
    assert float(m_ref["loss"]) == float(m_bkt["loss"])
    jax.tree.map(lambda a, c: np.testing.assert_array_equal(a, c),
                 s_ref.params, s_bkt.params)


def test_bn_model_refused_by_name():
    """The step body refuses batch_stats-carrying state at trace time
    (run_training refuses earlier, by model, with the same words)."""
    import types
    from distributedtensorflowexample_tpu.parallel.bucketing import (
        build_bucketed_step_fn)
    fn = build_bucketed_step_fn(0.0, "xla", make_mesh(), 8, 0, 1 << 20)
    fake = types.SimpleNamespace(batch_stats={"bn": 1})
    with pytest.raises(ValueError, match="BatchNorm"):
        fn(fake, {"image": None, "label": None})
    # and the builder itself refuses a mesh with nothing to reduce
    with pytest.raises(ValueError, match="multi-device"):
        build_bucketed_step_fn(0.0, "xla", None, 1, 0, 1 << 20)


# ---- knob resolution + planning ---------------------------------------

def test_resolve_bucket_bytes(monkeypatch):
    assert resolve_bucket_bytes("") is None
    assert resolve_bucket_bytes("auto") == DEFAULT_BUCKET_BYTES
    monkeypatch.setenv("BUCKET_GRADS_AUTO_BYTES", "123456")
    assert resolve_bucket_bytes("auto") == 123456
    assert resolve_bucket_bytes("65536") == 65536
    with pytest.raises(ValueError, match="byte count"):
        resolve_bucket_bytes("bogus")
    with pytest.raises(ValueError, match="positive"):
        resolve_bucket_bytes("0")
    # The env override goes through the SAME validation: 0 must not
    # silently disable the bucketing the flag explicitly asked for.
    monkeypatch.setenv("BUCKET_GRADS_AUTO_BYTES", "0")
    with pytest.raises(ValueError, match="BUCKET_GRADS_AUTO_BYTES"):
        resolve_bucket_bytes("auto")
    monkeypatch.setenv("BUCKET_GRADS_AUTO_BYTES", "junk")
    with pytest.raises(ValueError, match="BUCKET_GRADS_AUTO_BYTES"):
        resolve_bucket_bytes("auto")


def test_bucket_rows_restore_refusals():
    """Layout guards: a legacy checkpoint (no update_layout key) can only
    hold the params-shaped tree — it must be refused into a bucket_rows
    run by name, and bucket_rows across mesh sizes is structural (the
    1/D row layout could restore PERMUTED, not just shape-mismatched)."""
    from distributedtensorflowexample_tpu.trainers.common import (
        _refuse_incompatible_restore)
    cur = {"sync_mode": "sync", "mesh_size": 8, "num_workers": None,
           "update_layout": "bucket_rows"}
    with pytest.raises(ValueError, match="'tree'"):
        _refuse_incompatible_restore({"sync_mode": "sync", "mesh_size": 8},
                                     cur, "/l", True)
    with pytest.raises(ValueError, match="structural"):
        _refuse_incompatible_restore(
            {"sync_mode": "sync", "mesh_size": 4,
             "update_layout": "bucket_rows"}, cur, "/l", True)
    # tree->tree across mesh sizes stays allowed (sync state replicated)
    cur_t = dict(cur, update_layout="tree")
    _refuse_incompatible_restore(
        {"sync_mode": "sync", "mesh_size": 4, "update_layout": "tree"},
        cur_t, "/l", False)
    # zero3_rows (PR 12): params themselves are 1/D rows — the same
    # structural refusals, by the layout's name
    cur_z = dict(cur, update_layout="zero3_rows")
    with pytest.raises(ValueError, match="zero3_rows"):
        _refuse_incompatible_restore(
            {"sync_mode": "sync", "mesh_size": 8, "update_layout": "tree"},
            cur_z, "/l", True)
    with pytest.raises(ValueError, match="structural"):
        _refuse_incompatible_restore(
            {"sync_mode": "sync", "mesh_size": 4,
             "update_layout": "zero3_rows"}, cur_z, "/l", True)


def test_plan_buckets_and_padding():
    mk = lambda shape, dt=np.float32: np.zeros(shape, dt)
    leaves = [mk(100), mk(200), mk(50, np.int32), mk(4000)]
    # dtype change forces a split; the cap forces another
    plan = plan_buckets(leaves, 1300 * 4)
    assert plan == [[0, 1], [2], [3]]
    assert [i for b in plan for i in b] == list(range(4))  # order kept
    # an over-cap leaf still gets its own bucket, never split
    assert plan_buckets([mk(10_000)], 4) == [[0]]
    assert bucket_padding_bytes([mk(10), mk(16)], 8) == 6 * 4


# ---- the characterization bench ---------------------------------------

def test_knee_fit_and_bucket_suggestion():
    """fit_latency_bandwidth recovers an exact alpha/beta, tolerates
    noise, and degrades (knee None) instead of fitting garbage."""
    alpha, beta = 2e-4, 5e8
    sizes = [4096.0 * 4 ** k for k in range(6)]
    times = [alpha + s / beta for s in sizes]
    fit = bench_collectives.fit_latency_bandwidth(sizes, times)
    assert abs(fit["alpha_s"] - alpha) < 1e-9
    assert abs(fit["beta_bytes_per_s"] - beta) / beta < 1e-6
    assert abs(fit["knee_bytes"] - alpha * beta) <= 1
    assert fit["r2"] > 0.9999
    noisy = [t * (1 + 0.05 * (-1) ** i) for i, t in enumerate(times)]
    assert bench_collectives.fit_latency_bandwidth(sizes, noisy)[
        "knee_bytes"] > 0
    assert bench_collectives.fit_latency_bandwidth([1], [1])[
        "knee_bytes"] is None
    assert bench_collectives.fit_latency_bandwidth(
        sizes, list(reversed(times)))["knee_bytes"] is None  # negative slope
    assert bench_collectives.suggest_bucket_bytes(None) is None
    assert bench_collectives.suggest_bucket_bytes(1) == 256 << 10   # clamp
    assert bench_collectives.suggest_bucket_bytes(1 << 30) == 64 << 20
    assert bench_collectives.suggest_bucket_bytes(250_000) == 1_000_000


def test_sentinel_record_shape(tmp_path):
    """The down-backend sentinel is a BENCH-family line a capture can
    archive: provisional, probe attempts preserved, never mistakable
    for a measurement."""
    import argparse
    out = tmp_path / "coll.json"
    bench_collectives._sentinel(
        argparse.Namespace(json=str(out)), ["t+0s: probe timed out"])
    import json
    rec = json.load(open(out))
    assert rec["unit"] == "unavailable"
    assert rec["detail"]["provisional"] is True
    assert rec["detail"]["probe_attempts"]


def test_bench_collectives_cli_smoke():
    """One tiny real sweep through the CLI (forced 8-device CPU mesh):
    JSON-lines points + a family summary + the --json artifact with the
    CPU labeling that keeps curves honest."""
    import json
    out = "/tmp/test_bench_collectives.json"
    env = dict(os.environ, PALLAS_AXON_POOL_IPS="", JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "bench_collectives.py", "--sizes", "4096,65536",
         "--submeshes", "8", "--collectives", "psum", "--repeats", "2",
         "--json", out],
        cwd=REPO, env=env, capture_output=True, timeout=420)
    assert proc.returncode == 0, proc.stderr[-800:]
    lines = [json.loads(l) for l in proc.stdout.splitlines() if l]
    points = [l for l in lines if "collective" in l]
    assert len(points) == 2
    assert all(p["platform"] == "cpu" for p in points)
    rec = json.load(open(out))
    assert rec["metric"] == "collective_allreduce_knee_bytes"
    assert rec["detail"]["forced_cpu_mesh"] is True
    assert rec["detail"]["chip"] is False
    assert "NEVER read as chip numbers" in rec["detail"]["note"]
    assert rec["detail"]["knees"]["psum"]["8"] is not None


# ---- obs wiring --------------------------------------------------------

def test_metrics_hook_collective_counters():
    from distributedtensorflowexample_tpu.obs import metrics as obs_metrics
    from distributedtensorflowexample_tpu.training.hooks import MetricsHook
    summary = {"multiset": {"all-reduce": 3},
               "per_step": {"all-reduce": {"count": 3, "out_bytes": 31408,
                                           "accounting_bytes": 62816}},
               "total_count_per_step": 3,
               "total_out_bytes_per_step": 31408}
    before = obs_metrics.registry().snapshot()["counters"]
    hook = MetricsHook(every=10, collectives=summary)

    class _Loop:
        start_step = 0
    hook.begin(_Loop())
    hook.after_step(4, None, {})      # a 4-step fused boundary
    hook.after_step(8, None, {})
    after = obs_metrics.registry().snapshot()["counters"]

    def delta(name):
        return after.get(name, 0) - before.get(name, 0)
    assert delta("collective_ops_total") == 3 * 8
    assert delta("collective_bytes_total") == 31408 * 8
    gauges = obs_metrics.registry().snapshot()["gauges"]
    assert gauges['collective_ops_per_step{op="all-reduce"}']["value"] == 3
    # absent summary: no collective counting, hot path untouched
    h2 = MetricsHook(every=10)
    assert h2._coll_ops is None


def test_obs_report_collectives_section():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import obs_report
    finally:
        sys.path.pop(0)
    flight = {"reason": "exit", "pid": 1,
              "metrics": {
                  "counters": {"collective_ops_total": 120,
                               "collective_bytes_total": 1256320},
                  "gauges": {
                      'collective_ops_per_step{op="all-reduce"}':
                          {"value": 3},
                      'collective_bytes_per_step{op="all-reduce"}':
                          {"value": 31408}}}}
    text = obs_report.render_flight("flight_1.json", flight)
    assert "### Collectives" in text
    assert "`all-reduce`" in text
    assert "31408" in text
    assert "collective_bytes_total" in text
    # no collective series -> no section
    assert "### Collectives" not in obs_report.render_flight(
        "flight_2.json", {"metrics": {"counters": {"x": 1}}})


# ---- slow_rank straggler fault (satellite; grammar tests ride the
# fleet suite's patterns, behavior pinned here) --------------------------

def test_slow_rank_plan_and_determinism():
    from distributedtensorflowexample_tpu.resilience.faults import (
        NAMED_PLANS, FaultPlan)
    p1 = FaultPlan.parse("slow_rank@3:0.5%1", 10, seed=7)
    (s,) = p1.specs
    assert (s.kind, s.step, s.arg, s.rank) == ("slow_rank", 3, 0.5, 1)
    assert p1.loop_specs == p1.specs            # a loop-level fault
    assert not p1.for_rank(0).specs             # pinned to rank 1
    assert p1.for_rank(1).specs == p1.specs
    # named plan + default arg; unpinned step is seed-deterministic
    a = FaultPlan.parse("slow_rank", 20, seed=5).specs[0]
    b = FaultPlan.parse("slow_rank", 20, seed=5).specs[0]
    assert "slow_rank" in NAMED_PLANS
    assert a.step == b.step and a.arg == 0.25
    assert FaultPlan.parse("slow_rank:0.1", 20, seed=6).specs[0].arg == 0.1


def test_slow_rank_hook_delays_every_boundary_and_survives_resume():
    from distributedtensorflowexample_tpu.resilience.faults import (
        FaultInjectionHook, FaultPlan)

    class _Loop:
        start_step = 0

    delay = 0.05
    hook = FaultInjectionHook(FaultPlan.parse(f"slow_rank@2:{delay}", 10))
    hook.begin(_Loop())
    t0 = time.perf_counter()
    hook.after_step(1, None, {})
    assert time.perf_counter() - t0 < delay / 2     # not yet active
    for step in (2, 3):
        t0 = time.perf_counter()
        hook.after_step(step, None, {})
        assert time.perf_counter() - t0 >= delay    # every boundary after
    # resume past the fault step: the rank is STILL slow, but the
    # injection isn't re-counted as a fresh fault
    from distributedtensorflowexample_tpu.obs import metrics as obs_metrics
    before = obs_metrics.registry().snapshot()["counters"].get(
        'faults_injected_total{kind="slow_rank"}', 0)
    resumed = FaultInjectionHook(FaultPlan.parse(f"slow_rank@2:{delay}", 10))

    class _Resumed:
        start_step = 5
    resumed.begin(_Resumed())
    t0 = time.perf_counter()
    resumed.after_step(6, None, {})
    assert time.perf_counter() - t0 >= delay
    after = obs_metrics.registry().snapshot()["counters"].get(
        'faults_injected_total{kind="slow_rank"}', 0)
    assert after == before
