"""Benchmark harness — one JSON line per contract workload, headline LAST.

Headline (BASELINE.json "metric"): MNIST CNN steps/sec/chip, sync-SGD.
The reference published no numbers (BASELINE.json "published": {}), so
``vs_baseline`` is computed against this repo's own recorded baselines in
``BASELINE_SELF.json``.  Those denominators RATCHET each round to the
latest attested full run (round 3: the round-2 on-chip record, headline
1,681 steps/s/chip), so a ratio of ~1.0 means "held round-2 performance"
— lineage from the round-1 host-fed 590.8 is in BASELINE.md.

Workloads (BASELINE.md "must emit exactly this table's metrics"), in
MEASUREMENT order — the headline is measured first (recovery windows
between outages ran as short as ~9 min; the contract metric must land
while the window is alive) but always EMITTED last:
  config 3  mnist_cnn_sync          HEADLINE — unroll sweep + roofline
  config 4  cifar_resnet20          augmented, + MFU estimate
  config 2  mnist_cnn_async         local-SGD emulation, device-resident
  config 1  mnist_softmax           device-resident, fused steps
  variants  mnist_cnn pallas_ce / fused_sgd   (hand-written kernels)

Each line carries a ``detail`` object: every repeat (the chip sits behind
a shared tunnel with ~20x noisy-neighbor variance, so round-over-round
comparisons need the spread, not just the max), the unroll sweep, and a
pure-compute roofline probe (scanned fixed-batch steps, no per-call
dispatch) for the headline.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time
import traceback

import jax
import jax.numpy as jnp

REPEATS = 3
PEAK_FLOPS = float(os.environ.get("TPU_PEAK_FLOPS", 197e12))  # v5e bf16

# Workload sizing — module-level so the end-to-end smoke test
# (tests/test_bench_e2e.py) can shrink the SAME main() code path the
# driver runs, instead of faking pieces of it.  The driver's run uses
# these defaults unchanged.
DATA_DIR = "/tmp/data"
TRAIN_N = {"mnist": 60000, "cifar10": 50000}     # split sizes for sizing
BATCH = {"cnn": 256, "softmax": 100, "resnet": 256}   # per chip
MIN_STEPS = {"headline": 512, "resnet": 96}      # per measurement
ROOFLINE_LEN = {"headline": 256, "softmax": 2048, "resnet": 128}
# Sweep shapes as functions of steps-per-epoch.  Module-level for the
# same reason: each distinct unroll is a fresh XLA compile, and compile
# count (not step count) dominates the smoke test's cold runtime.
HEADLINE_REST_UNROLLS = lambda spe: {16, spe, 4 * spe, 8 * spe}
RESNET_UNROLLS = lambda spe: {8, 64, spe}

# Outage resilience (round-2 postmortem: a failed in-process backend init
# blocks 25-45 min and the driver runs bench exactly once per round, so a
# single outage window zeroed the round's official record).  Before paying
# the in-process init we probe the backend in a short-lived subprocess
# with a hard timeout, and retry on a schedule within a budget.
PROBE_TIMEOUT_S = float(os.environ.get("BENCH_PROBE_TIMEOUT_S", 300))
RETRY_INTERVAL_S = float(os.environ.get("BENCH_RETRY_INTERVAL_S", 240))
RETRY_BUDGET_S = float(os.environ.get("BENCH_RETRY_BUDGET_S", 2400))

# Hard wall-clock budget for the measurement phase itself.  Round 3
# measured the remaining failure mode the probe can't catch: the backend
# died ~5 min AFTER a successful probe and the next jit call blocked
# >60 min without raising — a driver run stuck that way records nothing
# at all, which is strictly worse than the sentinel.  A watchdog THREAD
# works here because XLA compile/execute calls release the GIL while
# blocked; on expiry it emits the sentinel headline (the per-workload
# lines already printed remain valid — each is flushed as it completes)
# and hard-exits.  os._exit is deliberate: the main thread is wedged
# inside a C++ call that will never return, so normal interpreter
# shutdown would block on it forever.
TOTAL_BUDGET_S = float(os.environ.get("BENCH_TOTAL_BUDGET_S", 5400))

# The probe must FAIL on a silent fall-back-to-CPU init (jax can degrade
# with only a warning): a CPU measurement published as steps/sec/chip is
# exactly the mislabeled record the sentinel machinery exists to prevent.
# Checked as `platform != cpu` (not == tpu) because the axon plugin's
# platform string is plugin-defined.
_PROBE_CODE = (
    "import jax; d = jax.devices();"
    " assert d[0].platform != 'cpu', f'CPU fallback: {d}';"
    " x = jax.numpy.ones((128, 128)); (x @ x).block_until_ready();"
    " print('PROBE_OK', len(d), d[0].platform)"
)


def _probe_backend(timeout_s: float = PROBE_TIMEOUT_S) -> tuple[bool, str]:
    """Touch the backend (import + tiny matmul) in a subprocess so a hung
    init costs ``timeout_s``, not 25-45 min of the driver's run.  SIGTERM
    with a grace period before SIGKILL: hard-killing a process mid-init
    has wedged the shared tunnel before (see docs/DESIGN.md)."""
    proc = subprocess.Popen(
        [sys.executable, "-c", _PROBE_CODE],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE)
    try:
        out, err = proc.communicate(timeout=timeout_s)
        if proc.returncode == 0 and b"PROBE_OK" in out:
            return True, out.decode(errors="replace").strip()
        tail = err.decode(errors="replace").strip().splitlines()[-3:]
        return False, f"rc={proc.returncode} " + " | ".join(tail)[:300]
    except subprocess.TimeoutExpired:
        proc.terminate()
        try:
            # communicate (not wait): reaps AND drains/closes the pipes —
            # wait() leaks both PIPE fds every retry and discards the
            # partial stderr that explains the hang.
            _, err = proc.communicate(timeout=30)
        except subprocess.TimeoutExpired:
            proc.kill()
            _, err = proc.communicate()
        tail = err.decode(errors="replace").strip().splitlines()[-2:]
        return False, (f"probe timed out after {timeout_s:.0f}s"
                       + (f" | {' | '.join(tail)}"[:200] if tail else ""))


def _cpu_pinned() -> bool:
    """True when this run can't touch the TPU tunnel anyway — probing
    would only spawn a subprocess that tries to (tests pin CPU via
    jax.config, not the env var, because sitecustomize overrides
    JAX_PLATFORMS)."""
    return (os.environ.get("BENCH_SKIP_PROBE") == "1"
            or os.environ.get("JAX_PLATFORMS", "").lower() == "cpu"
            or getattr(jax.config, "jax_platforms", None) == "cpu")


def _wait_for_backend() -> tuple[bool, list]:
    """Probe-with-retries inside RETRY_BUDGET_S.  Returns (reachable,
    attempt log).  Skipped when the run is pinned to CPU (tests) or via
    BENCH_SKIP_PROBE=1."""
    if _cpu_pinned():
        return True, ["probe skipped (cpu platform or BENCH_SKIP_PROBE)"]
    deadline = time.time() + RETRY_BUDGET_S
    attempts = []
    while True:
        t0 = time.time()
        ok, info = _probe_backend()
        attempts.append(f"t+{t0 - deadline + RETRY_BUDGET_S:.0f}s: {info}")
        # stderr heartbeat only — stdout is a pure JSON-lines protocol.
        print(f"bench: backend probe {attempts[-1]}", file=sys.stderr,
              flush=True)
        if ok:
            return True, attempts
        if time.time() + RETRY_INTERVAL_S + PROBE_TIMEOUT_S > deadline:
            return False, attempts
        time.sleep(RETRY_INTERVAL_S)


def _arm_watchdog(budget_s: float, fire, _exit=os._exit) -> threading.Event:
    """Daemon timer that calls ``fire()`` and hard-exits (code 3) if the
    returned Event isn't set within ``budget_s``.  Covers the failure the
    probe can't: a jit call that blocks forever after the backend dies
    mid-run (XLA compile/execute releases the GIL, so this thread runs
    while the main thread is wedged in C++).  ``os._exit`` because normal
    shutdown would join the wedged call; by the time the watchdog fires
    the tunnel is already gone, so the skip-atexit exit can't wedge a
    healthy chip."""
    done = threading.Event()

    def watch():
        if not done.wait(budget_s):
            try:
                fire()
                sys.stdout.flush()
            finally:
                # The exit must survive a failing fire() (e.g. stdout
                # gone, or a dict mutated mid-serialization): a watchdog
                # that dies before exiting recreates the silent hang it
                # exists to prevent.
                _exit(3)

    threading.Thread(target=watch, daemon=True, name="bench-watchdog").start()
    return done


def _load_baselines() -> dict:
    if os.path.exists("BASELINE_SELF.json"):
        try:
            with open("BASELINE_SELF.json") as f:
                return json.load(f)
        except (json.JSONDecodeError, OSError):
            pass
    return {}


def _emit(metric: str, per_chip: float, baselines: dict, detail: dict) -> None:
    baseline = baselines.get(metric)
    print(json.dumps({
        "metric": metric,
        "value": round(per_chip, 2),
        "unit": "steps/sec/chip",
        "vs_baseline": round(per_chip / baseline, 4) if baseline else 1.0,
        "detail": detail,
    }), flush=True)


def _measure(step, ds, state, steps: int, unroll: int,
             warmup_calls: int = 2) -> tuple[float, list, object]:
    """Best-of-REPEATS steady-state rate; each repeat blocks on its own
    final metrics so a queue flush can't masquerade as throughput."""
    calls = max(1, steps // unroll)
    actual_steps = calls * unroll
    metrics = None
    for _ in range(warmup_calls):
        state, metrics = step(state, next(ds))
    jax.block_until_ready(metrics)
    rates = []
    for _ in range(REPEATS):
        t0 = time.perf_counter()
        for _ in range(calls):
            state, metrics = step(state, next(ds))
        jax.block_until_ready(metrics)
        rates.append(actual_steps / (time.perf_counter() - t0))
    return max(rates), [round(r, 1) for r in rates], state


def _sweep(unrolls, make_fn, steps_for, err_prefix: str, errors: dict):
    """Measure every unroll in ``unrolls`` (largest first, so if the tunnel
    dies mid-sweep the best candidate is already on record), each point
    fault-isolated into ``errors``.  Returns
    (best_rate, best_unroll, best_repeats, {unroll: repeats})."""
    sweep = {}
    best_overall, best_unroll, best_rates = 0.0, None, []
    for unroll in sorted(unrolls, reverse=True):
        try:
            step, ds, state, u = make_fn(unroll)
            # Keep the success/error keyspaces aligned (errors key by the
            # *requested* unroll) — a factory that normalizes the unroll
            # would silently fork them.
            assert u == unroll, f"factory changed unroll {unroll} -> {u}"
            best, rates, _ = _measure(step, ds, state, steps_for(u), u)
            sweep[str(u)] = rates
            if best > best_overall:
                best_overall, best_unroll, best_rates = best, u, rates
        except Exception as e:
            errors[f"{err_prefix}{unroll}"] = repr(e)
            traceback.print_exc()
    return best_overall, best_unroll, best_rates, sweep


def _make(model_name: str, dataset: str, batch_per_chip: int, unroll: int,
          mesh, *, momentum: float = 0.9, ce_impl: str = "xla",
          fused_opt: bool = False, augment: str = "none", lr: float = 0.05,
          sync: bool = True, async_period: int = 8,
          data_dir: str | None = None):
    import optax

    from distributedtensorflowexample_tpu.data import DeviceDataset
    from distributedtensorflowexample_tpu.data.cifar10 import load_cifar10
    from distributedtensorflowexample_tpu.data.mnist import load_mnist
    from distributedtensorflowexample_tpu.models import build_model
    from distributedtensorflowexample_tpu.parallel import replicated_sharding
    from distributedtensorflowexample_tpu.parallel.async_ps import (
        make_indexed_async_train_step, make_worker_state)
    from distributedtensorflowexample_tpu.parallel.sync import (
        make_indexed_train_step)
    from distributedtensorflowexample_tpu.training.state import TrainState

    num_chips = mesh.size
    global_batch = batch_per_chip * num_chips
    load = load_mnist if dataset == "mnist" else load_cifar10
    sample = (28, 28, 1) if dataset == "mnist" else (32, 32, 3)
    # Resolved at call time (not def time) so tests can repoint DATA_DIR.
    train_x, train_y = load(data_dir if data_dir is not None else DATA_DIR,
                            "train")
    ds = DeviceDataset(train_x, train_y, global_batch, mesh=mesh, seed=0,
                       steps_per_next=unroll)

    model = build_model(model_name, dropout=0.5)
    if fused_opt:
        from distributedtensorflowexample_tpu.ops.pallas import (
            fused_momentum_sgd)
        tx = fused_momentum_sgd(lr, momentum=momentum, mesh=mesh)
    elif momentum > 0:
        tx = optax.sgd(lr, momentum=momentum)
    else:
        tx = optax.sgd(lr)
    state = TrainState.create_sharded(
        model, tx, (global_batch,) + sample, 0, replicated_sharding(mesh))
    if sync:
        step = make_indexed_train_step(global_batch, ds.steps_per_epoch,
                                       mesh=mesh, unroll_steps=unroll,
                                       ce_impl=ce_impl, augment=augment,
                                       num_slots=ds.num_slots)
    else:
        state = make_worker_state(state, num_chips, mesh)
        step = make_indexed_async_train_step(
            num_chips, async_period, global_batch, ds.steps_per_epoch,
            ce_impl=ce_impl, mesh=mesh, unroll_steps=unroll, augment=augment,
            num_slots=ds.num_slots)
    return step, ds, state, unroll


def _roofline_probe(mesh, batch_per_chip: int, length: int = 256,
                    model_name: str = "mnist_cnn",
                    sample: tuple = (28, 28, 1), lr: float = 0.05,
                    momentum: float = 0.9) -> list:
    """Pure device step rate: `length` model steps scanned over a FIXED
    resident batch in one compiled call — no gather, no augment, no
    per-call dispatch.  The gap between this and the measured path is
    input/dispatch (and, for augmented workloads, augmentation) overhead.
    Run in the same process/window as the measurement it calibrates: the
    shared chip's ~10-20x neighbor variance makes cross-window absolute
    numbers meaningless (BASELINE_SELF.json note)."""
    import optax

    from distributedtensorflowexample_tpu.data.synthetic import make_synthetic
    from distributedtensorflowexample_tpu.models import build_model
    from distributedtensorflowexample_tpu.parallel import (
        batch_sharding, replicated_sharding)
    from distributedtensorflowexample_tpu.parallel.sync import _build_step_fn
    from distributedtensorflowexample_tpu.training.state import TrainState

    global_batch = batch_per_chip * mesh.size
    x, y = make_synthetic(global_batch, sample, 10, seed=0)
    batch = jax.device_put({"image": jnp.asarray(x), "label": jnp.asarray(y)},
                           batch_sharding(mesh))
    model = build_model(model_name, dropout=0.5)
    tx = optax.sgd(lr, momentum=momentum) if momentum > 0 else optax.sgd(lr)
    state = TrainState.create_sharded(
        model, tx, (global_batch,) + sample, 0, replicated_sharding(mesh))
    inner = _build_step_fn(mesh=mesh)

    @jax.jit
    def probe(state, batch):
        new_state, stacked = jax.lax.scan(
            lambda st, _: inner(st, batch), state, None, length=length)
        return new_state, jax.tree.map(lambda m: m[-1], stacked)

    state, metrics = probe(state, batch)
    jax.block_until_ready(metrics)
    rates = []
    for _ in range(REPEATS):
        t0 = time.perf_counter()
        state, metrics = probe(state, batch)
        jax.block_until_ready(metrics)
        rates.append(length / (time.perf_counter() - t0))
    return [round(r, 1) for r in rates]


def _cost_per_step(step, state, data, unroll: int) -> dict:
    """Per-step flops and bytes accessed from the compiled module's cost
    analysis (best-effort: backends differ in which keys they report)."""
    out = {}
    try:
        cost = step.lower(state, data).compile().cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0]
        for key, name in (("flops", "flops"),
                          ("bytes accessed", "bytes_accessed")):
            if key in cost:
                out[name] = float(cost[key]) / unroll
    except Exception:
        pass
    return out


def _flops_per_step(step, state, data, unroll: int) -> float | None:
    return _cost_per_step(step, state, data, unroll).get("flops")


def main() -> None:
    """Each workload is fault-isolated: one failing config (e.g. the
    tunnel dropping mid-run) must not stop the later lines — above all
    the HEADLINE, which is always the last line emitted."""
    from distributedtensorflowexample_tpu.parallel import make_mesh

    def emit_unavailable(why: str, attempts: list,
                         errors: dict | None = None) -> None:
        # Sentinel, NOT a measurement: unit "unavailable" + value 0.0 so
        # no consumer can mistake the line for a measured 100% regression
        # (round 2's 0.0 steps/sec/chip line read exactly that way).
        detail = {"error": why[:500], "probe_attempts": attempts[-8:],
                  "see": "BENCH_early_r03.json (round-3 early capture), "
                         "BENCH_manual_r02.json (full on-chip run, "
                         "2026-07-30), and BASELINE.md"}
        if errors:
            # Attached structurally (not serialized into a truncated
            # string) so the headline sweep's own per-point errors — the
            # LAST dict entries — can't be cut off by earlier workloads'.
            # list() snapshots first: the watchdog thread may serialize
            # while the main thread is still appending.
            detail["errors"] = {k: v[:300] for k, v in list(errors.items())}
        print(json.dumps({
            "metric": "mnist_cnn_sync_steps_per_sec_per_chip",
            "value": 0.0, "unit": "unavailable", "vs_baseline": 0.0,
            "detail": detail,
        }), flush=True)

    reachable, attempts = _wait_for_backend()
    if not reachable:
        emit_unavailable(
            "TPU backend unreachable after probe retries "
            f"(budget {RETRY_BUDGET_S:.0f}s)", attempts)
        return
    errors: dict = {}
    # The headline is measured FIRST but emitted LAST (see the workload
    # section); between those two points the finished line lives here so
    # a watchdog fire during a later side workload emits the REAL
    # measured headline instead of discarding it for the sentinel.
    held_headline: dict = {}

    def fire_watchdog():
        why = (f"watchdog: measurement phase exceeded {TOTAL_BUDGET_S:.0f}s"
               " — a call blocked without raising (backend presumed lost "
               "mid-run); any lines above are valid completed measurements")
        if held_headline:
            detail = dict(held_headline["detail"])
            detail["errors"] = {k: v[:300] for k, v in list(errors.items())}
            detail["watchdog"] = why
            _emit("mnist_cnn_sync_steps_per_sec_per_chip",
                  held_headline["per_chip"], _load_baselines(), detail)
        else:
            emit_unavailable(why, attempts, errors)

    # Armed BEFORE the in-process init: make_mesh is the next backend
    # touch and itself blocks 25-45 min if the backend died after the
    # probe succeeded.  Disarmed immediately after the headline emit.
    # If it fires, the headline (measured, or the sentinel) IS the last
    # line (per-workload lines already printed stay valid — each was
    # flushed as it completed).
    watchdog_done = _arm_watchdog(TOTAL_BUDGET_S, fire_watchdog)
    try:
        mesh = make_mesh()
    except Exception as e:
        emit_unavailable(f"TPU backend unavailable: {e!r}", attempts)
        watchdog_done.set()
        return
    num_chips = mesh.size
    baselines = _load_baselines()

    def attempt(name, fn):
        try:
            fn()
        except Exception as e:
            errors[name] = repr(e)
            traceback.print_exc()

    def attach_roofline(detail, best, name, batch_per_chip, **roofline_kw):
        """Same-window pure-compute probe + measured/roofline ratio —
        the ONE definition of the ratio (max of probe repeats), shared by
        every line that carries it."""
        roof: list = []
        attempt(name, lambda: roof.extend(
            _roofline_probe(mesh, batch_per_chip, **roofline_kw)))
        if roof:
            detail["roofline_probe"] = roof
            detail["vs_roofline"] = round(best / max(roof), 4)

    def run_simple(metric, model, dataset, batch_per_chip, unroll, steps,
                   extra_detail=None, roofline_kw=None, **make_kw):
        """Build + measure one workload and emit its line (the shape every
        non-headline config shares).  ``roofline_kw`` adds a same-window
        pure-compute probe + measured/roofline ratio so the line stays
        interpretable under the shared chip's cross-window variance."""
        step, ds, state, u = _make(model, dataset, batch_per_chip, unroll,
                                   mesh, **make_kw)
        best, rates, _ = _measure(step, ds, state, steps, u)
        detail = {"repeats": rates, "unroll": u,
                  "batch_per_chip": batch_per_chip, **(extra_detail or {})}
        if roofline_kw is not None:
            attach_roofline(detail, best, f"roofline_{metric}",
                            batch_per_chip, **roofline_kw)
        _emit(metric, best / num_chips, baselines, detail)

    def config4():
        # Round-2 measured ~43 ms/call dispatch through the degraded
        # tunnel; at unroll 8 that dispatch alone caps ResNet at ~186
        # steps/s, so the number said nothing about compute.  Sweep up to
        # a full epoch per call (spe = 195 at batch 256).
        b_rn = BATCH["resnet"]
        spe_cifar = TRAIN_N["cifar10"] // (b_rn * num_chips)
        flops_box: list = []   # at-most-once cost probe across sweep points

        def mk(unroll):
            step, ds, state, u = _make("resnet20", "cifar10", b_rn, unroll,
                                       mesh, augment="cifar", lr=0.1)
            if not flops_box:
                # peek, not next: the probe must not advance the ring ahead
                # of state.step, or a later window would read an evicted
                # perm row.
                flops_box.append(_flops_per_step(step, state, ds.peek(), u))
            return step, ds, state, u

        best_overall, best_unroll, best_rates, sweep = _sweep(
            RESNET_UNROLLS(spe_cifar), mk,
            lambda u: max(MIN_STEPS["resnet"], 2 * u),
            "resnet_sweep_", errors)
        if best_unroll is None:
            # Every point failed: emit nothing (a 0.0 line would read as a
            # silent 100% regression); the errors ride the headline line.
            return
        flops = flops_box[0] if flops_box else None
        per_chip = best_overall / num_chips
        # flops is whole-module (all devices); MFU = F*S_global/(N*peak)
        # = F*per_chip/peak.
        mfu = (flops * per_chip / PEAK_FLOPS) if flops else None
        # Same-window pure-compute roofline (scanned fixed batch, NO
        # augment/gather): the measured/roofline gap is the input+augment+
        # dispatch share — the attribution the MFU number alone can't give.
        detail = {"repeats": best_rates, "best_unroll": best_unroll,
                  "unroll_sweep": sweep, "batch_per_chip": b_rn,
                  "flops_per_step": flops,
                  "mfu": round(mfu, 4) if mfu is not None else None}
        attach_roofline(detail, best_overall, "roofline_resnet", b_rn,
                        length=ROOFLINE_LEN["resnet"], model_name="resnet20",
                        sample=(32, 32, 3), lr=0.1)
        _emit("cifar_resnet20_steps_per_sec_per_chip", per_chip, baselines,
              detail)

    # Multi-epoch fused windows everywhere (the perm ring removed the
    # per-epoch unroll ceiling): softmax steps are ~10x shorter than CNN
    # steps so they need the deepest fusion; the kernel variants use the
    # same unroll as the headline sweep's 4-epoch point so their deltas
    # read directly against sweep["936"] (single-chip).
    b_cnn, b_sm = BATCH["cnn"], BATCH["softmax"]
    spe = TRAIN_N["mnist"] // (b_cnn * num_chips)
    # Softmax steps are ~10x shorter than CNN steps, so dispatch still
    # shows at unroll 2048 (~3.4 epochs); fuse 16 epochs per call like the
    # headline sweep's deepest point.
    spe_softmax = TRAIN_N["mnist"] // (b_sm * num_chips)
    with mesh:
        # --- config 3 HEADLINE: MNIST CNN sync, unroll sweep -------------
        # Measured FIRST, emitted LAST.  Round 3 measured a recovery
        # window of ~9 minutes between two outage stretches: a run that
        # saves the contract metric for the end captures side workloads
        # and loses the headline when the window closes mid-run.  So the
        # likely-best sweep point (deepest unroll — it won every recorded
        # sweep) runs first, its same-window roofline immediately after
        # (the vs_roofline ratio is the one number that survives chip-
        # sharing variance — it must come from the SAME window as the
        # measurement it calibrates), then the remaining sweep points;
        # the emit order (headline last) is preserved by holding the
        # finished line until the end.
        # Multi-epoch fused windows (the perm ring, data/device_dataset.py)
        # let the unroll go past an epoch: sweep up to 16 epochs per call
        # (even 43 ms/call of degraded-tunnel dispatch amortizes to <3%).
        mk_headline = lambda unroll: _make("mnist_cnn", "mnist", b_cnn,
                                           unroll, mesh)
        steps_for = lambda u: max(MIN_STEPS["headline"], u * 4)
        best_overall, best_unroll, best_rates, sweep = _sweep(
            {16 * spe}, mk_headline, steps_for, "sweep_", errors)
        headline_detail = {"repeats": best_rates, "best_unroll": best_unroll,
                           "unroll_sweep": sweep, "batch_per_chip": b_cnn}

        def hold_best(b, u, r):
            """Record (b, u, r) as the held headline.  From the first
            call on, a watchdog fire emits THIS measured line, not the
            sentinel (a wedged side workload must not discard a finished
            contract metric).  The roofline is RE-probed on every call:
            the ratio only means something when probe and measurement
            share a window, so a promoted later point must not inherit
            the first point's probe — and the stale keys are dropped
            first so a failed re-probe can't leave a cross-window ratio
            behind."""
            nonlocal best_overall, best_unroll, best_rates
            best_overall, best_unroll, best_rates = b, u, r
            headline_detail["repeats"] = r
            headline_detail["best_unroll"] = u
            headline_detail.pop("roofline_probe", None)
            headline_detail.pop("vs_roofline", None)
            attach_roofline(headline_detail, b, "roofline", b_cnn,
                            length=ROOFLINE_LEN["headline"])
            held_headline["per_chip"] = b / num_chips
            held_headline["detail"] = headline_detail

        if best_unroll is not None:
            hold_best(best_overall, best_unroll, best_rates)

        # Remaining sweep points (still before the side workloads); a
        # later point that beats — or replaces a failed — first point is
        # promoted into the held line.
        b2, u2, r2, s2 = _sweep(HEADLINE_REST_UNROLLS(spe), mk_headline,
                                steps_for, "sweep_", errors)
        sweep.update(s2)   # same dict as headline_detail["unroll_sweep"]
        if u2 is not None and b2 > best_overall:
            hold_best(b2, u2, r2)

        # Side workloads, most valuable first (the window may close any
        # time): the flagship ResNet, the async contract config, then
        # softmax and the kernel variants.
        attempt("resnet20", config4)
        attempt("cnn_async", lambda: run_simple(
            "mnist_cnn_async_steps_per_sec_per_chip", "mnist_cnn", "mnist",
            b_cnn, 4 * spe, 8 * spe, extra_detail={"async_period": 8},
            sync=False))
        attempt("softmax", lambda: run_simple(
            "mnist_softmax_steps_per_sec_per_chip", "softmax", "mnist",
            b_sm, 16 * spe_softmax, 32 * spe_softmax, momentum=0.0, lr=0.5,
            roofline_kw={"model_name": "softmax", "momentum": 0.0,
                         "lr": 0.5, "length": ROOFLINE_LEN["softmax"]}))
        attempt("pallas_ce", lambda: run_simple(
            "mnist_cnn_sync_pallas_ce_steps_per_sec_per_chip", "mnist_cnn",
            "mnist", b_cnn, 4 * spe, 8 * spe, ce_impl="pallas"))
        attempt("fused_sgd", lambda: run_simple(
            "mnist_cnn_sync_fused_sgd_steps_per_sec_per_chip", "mnist_cnn",
            "mnist", b_cnn, 4 * spe, 8 * spe, fused_opt=True))

        if best_unroll is None:
            # Every headline point failed — the backend died AFTER the
            # initial probe succeeded (mid-run outage, the round-3 03:49
            # UTC capture's exact failure shape).  A 0.0 steps/sec/chip
            # line would read as a measured 100% regression, so emit the
            # same explicit sentinel the up-front probe failure uses.
            emit_unavailable(
                "every headline sweep point failed (no measurement; "
                "mid-run backend loss is the known cause of this shape, "
                "but read detail.errors for the actual per-point failures)",
                attempts, errors)
            watchdog_done.set()
            return
        if errors:   # attached last so any side-workload failure shows too
            headline_detail["errors"] = errors
        _emit("mnist_cnn_sync_steps_per_sec_per_chip",
              best_overall / num_chips, baselines, headline_detail)
        # Disarm right at the emit (not after mesh.__exit__): a budget
        # lapse in the gap would append a sentinel AFTER a valid headline.
        watchdog_done.set()


if __name__ == "__main__":
    main()
