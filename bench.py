"""Benchmark harness — one JSON line per contract workload, headline LAST.

Headline (BASELINE.json "metric"): MNIST CNN steps/sec/chip, sync-SGD.
The reference published no numbers (BASELINE.json "published": {}), so
``vs_baseline`` is computed against this repo's own recorded baselines in
``BASELINE_SELF.json``.  Those denominators RATCHET each round to the
latest attested full run (round 3: the round-2 on-chip record, headline
1,681 steps/s/chip), so a ratio of ~1.0 means "held round-2 performance"
— lineage from the round-1 host-fed 590.8 is in BASELINE.md.

Workloads (BASELINE.md "must emit exactly this table's metrics"), in
MEASUREMENT order — the headline is measured first (recovery windows
between outages ran as short as ~9 min; the contract metric must land
while the window is alive) but always EMITTED last:
  config 3  mnist_cnn_sync          HEADLINE — unroll sweep + roofline
  config 4  cifar_resnet20          augmented, + MFU estimate
  config 2  mnist_cnn_async         local-SGD emulation, device-resident
  config 1  mnist_softmax           device-resident, fused steps
  variants  mnist_cnn pallas_ce / fused_sgd   (hand-written kernels)

Each line carries a ``detail`` object: every repeat (the chip sits behind
a shared tunnel with ~20x noisy-neighbor variance, so round-over-round
comparisons need the spread, not just the max), the unroll sweep, and a
pure-compute roofline probe (scanned fixed-batch steps, no per-call
dispatch) for the headline.
"""

from __future__ import annotations

import json
import os
import random
import signal
import subprocess
import sys
import threading
import time
import traceback

import jax
import jax.numpy as jnp

REPEATS = 3
PEAK_FLOPS = float(os.environ.get("TPU_PEAK_FLOPS", 197e12))  # v5e bf16

# Workload sizing — module-level so the end-to-end smoke test
# (tests/test_bench_e2e.py) can shrink the SAME main() code path the
# driver runs, instead of faking pieces of it.  The driver's run uses
# these defaults unchanged.
DATA_DIR = "/tmp/data"
TRAIN_N = {"mnist": 60000, "cifar10": 50000}     # split sizes for sizing
BATCH = {"cnn": 256, "softmax": 100, "resnet": 256}   # per chip
MIN_STEPS = {"headline": 512, "resnet": 96}      # per measurement
ROOFLINE_LEN = {"headline": 256, "softmax": 2048, "resnet": 128}
# Sweep shapes as functions of steps-per-epoch.  Module-level for the
# same reason: each distinct unroll is a fresh XLA compile, and compile
# count (not step count) dominates the smoke test's cold runtime.
HEADLINE_REST_UNROLLS = lambda spe: {16, spe, 4 * spe, 8 * spe}
RESNET_UNROLLS = lambda spe: {8, 64, spe}

# In-step dequant kernel for the resident splits (--dequant /
# BENCH_DEQUANT; the round-5 tax fix).  "auto" resolves per split through
# the ONE shared rule (data.device_dataset.resolve_dequant_impl — the
# affine fast path for MNIST/CIFAR) AND, in a full run, measures the
# alternative impls at the winning unroll (tools/ab_quantize.py's sweep
# promoted into the official record), auto-selecting the fastest into the
# headline; a named impl forces that kernel everywhere.  Every emitted
# line's detail carries the impl that actually ran ("dequant"), so each
# window's BENCH_*.json attests which path produced its numbers —
# AB_quantize_r05.json measured 4.1x between impls of the SAME workload,
# a spread no record is interpretable without.
DEQUANT = os.environ.get("BENCH_DEQUANT", "auto")
# Alternatives the auto A/B measures against the resolved default (whose
# own rate is the headline measurement itself).  Module-level so the e2e
# smoke can thin it: each impl is a fresh multi-minute XLA compile there.
DEQUANT_AB_IMPLS = ("onehot", "lut", "pallas")

# Outage resilience (round-2 postmortem: a failed in-process backend init
# blocks 25-45 min and the driver runs bench exactly once per round, so a
# single outage window zeroed the round's official record).  Before paying
# the in-process init we probe the backend in a short-lived subprocess
# with a hard timeout, and retry on a schedule within a budget.
PROBE_TIMEOUT_S = float(os.environ.get("BENCH_PROBE_TIMEOUT_S", 300))
RETRY_INTERVAL_S = float(os.environ.get("BENCH_RETRY_INTERVAL_S", 240))
# (VERDICT r3 #1c) The driver's outer timeout observably kills bench at
# ~23-25 min; a 40-min retry budget could never finish under the one
# consumer that matters (round 3's official record died sleeping in this
# loop: rc=124, nothing on stdout).  900 s gives up with the explicit
# sentinel well inside the driver's window; detached captures
# (tools/bench_capture.sh) may extend via BENCH_RETRY_BUDGET_S.
RETRY_BUDGET_S = float(os.environ.get("BENCH_RETRY_BUDGET_S", 900))

# Headline-only mode (BENCH_HEADLINE_ONLY=1): measure the contract
# metric + its same-window roofline and STOP — no second sweep half, no
# side workloads.  tools/bench_capture.sh runs this as phase 1 of a
# recovery window so the headline and the never-yet-captured ResNet
# attribution (bench_profile.py, phase 2) both land inside a short
# window (round 3 measured one at ~9 min) before the full bench
# (phase 3) spends the rest of it.
HEADLINE_ONLY = os.environ.get("BENCH_HEADLINE_ONLY") == "1"

# Hard wall-clock budget for the measurement phase itself.  Round 3
# measured the remaining failure mode the probe can't catch: the backend
# died ~5 min AFTER a successful probe and the next jit call blocked
# >60 min without raising — a driver run stuck that way records nothing
# at all, which is strictly worse than the sentinel.  A watchdog THREAD
# works here because XLA compile/execute calls release the GIL while
# blocked; on expiry it emits the sentinel headline (the per-workload
# lines already printed remain valid — each is flushed as it completes)
# and hard-exits.  os._exit is deliberate: the main thread is wedged
# inside a C++ call that will never return, so normal interpreter
# shutdown would block on it forever.
TOTAL_BUDGET_S = float(os.environ.get("BENCH_TOTAL_BUDGET_S", 5400))

# The probe must FAIL on a silent fall-back-to-CPU init (jax can degrade
# with only a warning): a CPU measurement published as steps/sec/chip is
# exactly the mislabeled record the sentinel machinery exists to prevent.
# Checked as `platform != cpu` (not == tpu) because the axon plugin's
# platform string is plugin-defined.
_PROBE_CODE = (
    "import jax; d = jax.devices();"
    " assert d[0].platform != 'cpu', f'CPU fallback: {d}';"
    " x = jax.numpy.ones((128, 128)); (x @ x).block_until_ready();"
    " print('PROBE_OK', len(d), d[0].platform)"
)


# Live probe subprocess, if any — the SIGTERM handler terminates it on
# the way out so a killed bench doesn't orphan a wedged axon-init child.
_PROBE_PROC: subprocess.Popen | None = None


def _probe_backend(timeout_s: float | None = None) -> tuple[bool, str]:
    """Touch the backend (import + tiny matmul) in a subprocess so a hung
    init costs ``timeout_s``, not 25-45 min of the driver's run.  SIGTERM
    with a grace period before SIGKILL: hard-killing a process mid-init
    has wedged the shared tunnel before (see docs/DESIGN.md).

    ``timeout_s=None`` reads PROBE_TIMEOUT_S at CALL time (not def time)
    so the --probe_timeout_s CLI knob and monkeypatched tests govern
    probes issued after startup — the watch log showed every probe of a
    215-probe outage burning exactly the def-time 300 s."""
    global _PROBE_PROC
    if timeout_s is None:
        timeout_s = PROBE_TIMEOUT_S
    proc = subprocess.Popen(
        [sys.executable, "-c", _PROBE_CODE],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE)
    _PROBE_PROC = proc
    try:
        out, err = proc.communicate(timeout=timeout_s)
        if proc.returncode == 0 and b"PROBE_OK" in out:
            return True, out.decode(errors="replace").strip()
        tail = err.decode(errors="replace").strip().splitlines()[-3:]
        return False, f"rc={proc.returncode} " + " | ".join(tail)[:300]
    except subprocess.TimeoutExpired:
        proc.terminate()
        try:
            # communicate (not wait): reaps AND drains/closes the pipes —
            # wait() leaks both PIPE fds every retry and discards the
            # partial stderr that explains the hang.
            _, err = proc.communicate(timeout=30)
        except subprocess.TimeoutExpired:
            proc.kill()
            _, err = proc.communicate()
        tail = err.decode(errors="replace").strip().splitlines()[-2:]
        return False, (f"probe timed out after {timeout_s:.0f}s"
                       + (f" | {' | '.join(tail)}"[:200] if tail else ""))
    finally:
        _PROBE_PROC = None


def _cpu_platform() -> bool:
    """True when this process is pinned to the CPU backend (tests pin via
    jax.config, not the env var, because sitecustomize overrides
    JAX_PLATFORMS)."""
    return (os.environ.get("JAX_PLATFORMS", "").lower() == "cpu"
            or getattr(jax.config, "jax_platforms", None) == "cpu")


def _cpu_pinned() -> bool:
    """True when the up-front backend probe should be skipped — CPU runs
    can't touch the tunnel, and BENCH_SKIP_PROBE=1 opts a real run out of
    probing.  NOT the right gate for the watchdog: a TPU run with
    BENCH_SKIP_PROBE=1 can still wedge mid-run (use _cpu_platform)."""
    return os.environ.get("BENCH_SKIP_PROBE") == "1" or _cpu_platform()


def _wait_for_backend(into: list | None = None) -> tuple[bool, list]:
    """Probe-with-retries inside RETRY_BUDGET_S.  Returns (reachable,
    attempt log).  ``into`` (when given) receives each attempt as it
    happens, so a SIGTERM handler firing mid-retry can report them.
    Skipped when the run is pinned to CPU (tests) or via
    BENCH_SKIP_PROBE=1."""
    attempts = into if into is not None else []
    if _cpu_pinned():
        attempts.append("probe skipped (cpu platform or BENCH_SKIP_PROBE)")
        return True, attempts
    from distributedtensorflowexample_tpu.obs.trace import span
    with span("probe") as span_attrs:
        deadline = time.time() + RETRY_BUDGET_S
        while True:
            t0 = time.time()
            ok, info = _probe_backend()
            attempts.append(f"t+{t0 - deadline + RETRY_BUDGET_S:.0f}s: {info}")
            # stderr heartbeat only — stdout is a pure JSON-lines protocol.
            print(f"bench: backend probe {attempts[-1]}", file=sys.stderr,
                  flush=True)
            span_attrs["probes"] = len(attempts)
            if ok:
                span_attrs["reachable"] = True
                return True, attempts
            # Jittered backoff (resilience round): every supervisor/watcher
            # retrying a shared tunnel on the same fixed 240-s grid probes in
            # synchronized bursts — the uniform +/-25% spread decorrelates
            # them, and the deadline check uses the ACTUAL sleep so the
            # budget math stays exact.
            sleep_s = RETRY_INTERVAL_S * (0.75 + 0.5 * random.random())
            if time.time() + sleep_s + PROBE_TIMEOUT_S > deadline:
                span_attrs["reachable"] = False
                return False, attempts
            time.sleep(sleep_s)


def _arm_watchdog(budget_s: float, fire, _exit=os._exit) -> threading.Event:
    """Daemon timer that calls ``fire()`` and hard-exits (code 3) if the
    returned Event isn't set within ``budget_s``.  Covers the failure the
    probe can't: a jit call that blocks forever after the backend dies
    mid-run (XLA compile/execute releases the GIL, so this thread runs
    while the main thread is wedged in C++).  ``os._exit`` because normal
    shutdown would join the wedged call; by the time the watchdog fires
    the tunnel is already gone, so the skip-atexit exit can't wedge a
    healthy chip."""
    done = threading.Event()

    def watch():
        if not done.wait(budget_s):
            try:
                fire()
                sys.stdout.flush()
                # Wedged-dispatch postmortem (no-op unless a recorder
                # is installed); the record above is already flushed,
                # so a telemetry failure costs nothing.
                try:
                    from distributedtensorflowexample_tpu.obs.recorder \
                        import dump_global
                    dump_global("watchdog")
                except Exception:
                    pass
            finally:
                # The exit must survive a failing fire() (e.g. stdout
                # gone, or a dict mutated mid-serialization): a watchdog
                # that dies before exiting recreates the silent hang it
                # exists to prevent.
                _exit(3)

    threading.Thread(target=watch, daemon=True, name="bench-watchdog").start()
    return done


def _load_baselines() -> dict:
    if os.path.exists("BASELINE_SELF.json"):
        try:
            with open("BASELINE_SELF.json") as f:
                return json.load(f)
        except (json.JSONDecodeError, OSError):
            pass
    return {}


# Flipped (permanently — the process is exiting) by the SIGTERM handler:
# print()/flush() on the shared BufferedWriter raise RuntimeError
# ("reentrant call") if the signal landed while the main thread was
# mid-write to stdout; os.write to the fd has no such guard.
_EMIT_RAW = False


def _println(line: str) -> None:
    """One record line to stdout — signal-safe in _EMIT_RAW mode."""
    if _EMIT_RAW:
        # Loop on short writes: a pipe with a partly-full buffer may
        # accept fewer bytes than a record larger than PIPE_BUF, and a
        # torn '{...partial' tail is exactly what this path must never
        # leave.  EPIPE/EAGAIN: the reader is gone or stalled — nothing
        # more can be recorded, give up rather than spin.
        buf = (line + "\n").encode()
        while buf:
            try:
                n = os.write(1, buf)
            except OSError:
                return
            buf = buf[n:]
    else:
        print(line, flush=True)


def _emit(metric: str, per_chip: float, baselines: dict, detail: dict) -> None:
    baseline = baselines.get(metric)
    if detail.get("repeats") and "spread_frac" not in detail:
        # Measurement-instability sentinel (obs/anomaly.spread_fraction,
        # stdlib-only): (max-min)/max over the repeats.  A wide spread
        # marks the window as noisy IN the record, so the ratchet
        # (tools/bench_ratchet.py) can refuse to call a regression
        # "unexplained" off a measurement that disagrees with itself.
        from distributedtensorflowexample_tpu.obs.anomaly import (
            spread_fraction)
        detail["spread_frac"] = round(spread_fraction(detail["repeats"]), 4)
    _println(json.dumps({
        "metric": metric,
        "value": round(per_chip, 2),
        "unit": "steps/sec/chip",
        "vs_baseline": round(per_chip / baseline, 4) if baseline else 1.0,
        "detail": detail,
    }))


def _measure(step, ds, state, steps: int, unroll: int,
             warmup_calls: int = 2) -> tuple[float, list, object]:
    """Best-of-REPEATS steady-state rate; each repeat blocks on its own
    final metrics so a queue flush can't masquerade as throughput.

    Wrapped in an obs span (stdlib-only import, see obs/): under a
    supervised capture the span inherits OBS_PHASE from the queue task,
    so the telemetry names the same phases the capture journal does.
    The span closes once per MEASUREMENT (never per step) — zero cost
    on the rates themselves."""
    from distributedtensorflowexample_tpu.obs.trace import span
    with span("measure", steps=steps, unroll=unroll) as attrs:
        calls = max(1, steps // unroll)
        actual_steps = calls * unroll
        metrics = None
        for _ in range(warmup_calls):
            state, metrics = step(state, next(ds))
        jax.block_until_ready(metrics)
        rates = []
        for _ in range(REPEATS):
            t0 = time.perf_counter()
            for _ in range(calls):
                state, metrics = step(state, next(ds))
            jax.block_until_ready(metrics)
            rates.append(actual_steps / (time.perf_counter() - t0))
        attrs["best_steps_per_sec"] = round(max(rates), 1)
    return max(rates), [round(r, 1) for r in rates], state


def _sweep(unrolls, make_fn, steps_for, err_prefix: str, errors: dict):
    """Measure every unroll in ``unrolls`` (largest first, so if the tunnel
    dies mid-sweep the best candidate is already on record), each point
    fault-isolated into ``errors``.  Returns
    (best_rate, best_unroll, best_repeats, {unroll: repeats})."""
    sweep = {}
    best_overall, best_unroll, best_rates = 0.0, None, []
    for unroll in sorted(unrolls, reverse=True):
        try:
            step, ds, state, u = make_fn(unroll)
            # Keep the success/error keyspaces aligned (errors key by the
            # *requested* unroll) — a factory that normalizes the unroll
            # would silently fork them.
            assert u == unroll, f"factory changed unroll {unroll} -> {u}"
            best, rates, _ = _measure(step, ds, state, steps_for(u), u)
            sweep[str(u)] = rates
            if best > best_overall:
                best_overall, best_unroll, best_rates = best, u, rates
        except Exception as e:
            errors[f"{err_prefix}{unroll}"] = repr(e)
            traceback.print_exc()
    return best_overall, best_unroll, best_rates, sweep


def _make(model_name: str, dataset: str, batch_per_chip: int, unroll: int,
          mesh, *, momentum: float = 0.9, ce_impl: str = "xla",
          fused_opt: bool = False, augment: str = "none", lr: float = 0.05,
          sync: bool = True, async_period: int = 8,
          data_dir: str | None = None, dequant_impl: str = "auto"):
    """One knob config as an Engine declaration (engine/engine.py —
    the same construction stack run_training wires, minus hooks).  The
    input_fn/optimizer_fn seams carry the two bench-only policies: the
    fallback data source (the bench must run on a data-less chip host)
    and the bare float-LR optimizer (a schedule-wrapped twin has a
    DIFFERENT opt_state pytree — the step program must stay the
    measured trainer program, bitwise)."""
    from distributedtensorflowexample_tpu.config import RunConfig
    from distributedtensorflowexample_tpu.engine import Engine, RunSpec

    def input_fn(cfg, split):
        from distributedtensorflowexample_tpu.data.cifar10 import (
            load_cifar10)
        from distributedtensorflowexample_tpu.data.mnist import load_mnist
        load = load_mnist if dataset == "mnist" else load_cifar10
        # Resolved at call time (not def time) so tests can repoint
        # DATA_DIR.
        return load(data_dir if data_dir is not None else DATA_DIR,
                    split, source="fallback")

    def optimizer_fn(cfg, _mesh, wrap_shard_update):
        import optax
        if fused_opt:
            from distributedtensorflowexample_tpu.ops.pallas import (
                fused_momentum_sgd)
            return fused_momentum_sgd(lr, momentum=momentum, mesh=_mesh)
        if momentum > 0:
            return optax.sgd(lr, momentum=momentum)
        return optax.sgd(lr)

    cfg = RunConfig(batch_size=batch_per_chip, seed=0,
                    learning_rate=lr, momentum=momentum,
                    sync_mode="sync" if sync else "async",
                    async_period=async_period,
                    pallas_ce=(ce_impl == "pallas"),
                    fused_optimizer=fused_opt,
                    dequant_impl=dequant_impl)
    spec = RunSpec(model=model_name, dataset=dataset, config=cfg,
                   augment=(augment == "cifar"), input_fn=input_fn,
                   optimizer_fn=optimizer_fn)
    built = Engine(spec).build(mesh=mesh, unroll=unroll)
    return built.step, built.ds, built.state, built.unroll


def _roofline_probe(mesh, batch_per_chip: int, length: int = 256,
                    model_name: str = "mnist_cnn",
                    sample: tuple = (28, 28, 1), lr: float = 0.05,
                    momentum: float = 0.9,
                    cost_out: dict | None = None) -> list:
    """Pure device step rate: `length` model steps scanned over a FIXED
    resident batch in one compiled call — no gather, no augment, no
    per-call dispatch.  The gap between this and the measured path is
    input/dispatch (and, for augmented workloads, augmentation) overhead.
    Run in the same process/window as the measurement it calibrates: the
    shared chip's ~10-20x neighbor variance makes cross-window absolute
    numbers meaningless (BASELINE_SELF.json note)."""
    import optax

    from distributedtensorflowexample_tpu.data.synthetic import make_synthetic
    from distributedtensorflowexample_tpu.models import build_model
    from distributedtensorflowexample_tpu.parallel import (
        batch_sharding, replicated_sharding)
    from distributedtensorflowexample_tpu.parallel.sync import _build_step_fn
    from distributedtensorflowexample_tpu.training.state import TrainState

    global_batch = batch_per_chip * mesh.size
    x, y = make_synthetic(global_batch, sample, 10, seed=0)
    batch = jax.device_put({"image": jnp.asarray(x), "label": jnp.asarray(y)},
                           batch_sharding(mesh))
    model = build_model(model_name, dropout=0.5)
    tx = optax.sgd(lr, momentum=momentum) if momentum > 0 else optax.sgd(lr)
    state = TrainState.create_sharded(
        model, tx, (global_batch,) + sample, 0, replicated_sharding(mesh))
    inner = _build_step_fn(mesh=mesh)

    @jax.jit
    def probe(state, batch):
        new_state, stacked = jax.lax.scan(
            lambda st, _: inner(st, batch), state, None, length=length)
        return new_state, jax.tree.map(lambda m: m[-1], stacked)

    if cost_out is not None:
        # Per-step flops/bytes of the PROBE program — the denominator of
        # the measured-vs-roofline cost decomposition (the measured
        # path's extra bytes are the gather/ring/augment traffic the
        # probe deliberately lacks).
        cost_out.update(_cost_per_step(probe, state, batch, length))
    state, metrics = probe(state, batch)
    jax.block_until_ready(metrics)
    rates = []
    for _ in range(REPEATS):
        t0 = time.perf_counter()
        state, metrics = probe(state, batch)
        jax.block_until_ready(metrics)
        rates.append(length / (time.perf_counter() - t0))
    return [round(r, 1) for r in rates]


def _cost_per_step(step, state, data, unroll: int) -> dict:
    """Per-step flops and bytes accessed from the compiled module's cost
    analysis (best-effort: backends differ in which keys they report).
    Delegates to the ONE extraction implementation
    (utils.profiling.cost_and_bytes_audit, audit half skipped) so bench
    and profile records can never drift on the aggregate convention."""
    from distributedtensorflowexample_tpu.utils.profiling import (
        cost_and_bytes_audit)
    cost, _ = cost_and_bytes_audit(step, (state, data), unroll=unroll,
                                   audit=False)
    return cost


def _flops_per_step(step, state, data, unroll: int) -> float | None:
    return _cost_per_step(step, state, data, unroll).get("flops")


def main() -> None:
    """Each workload is fault-isolated: one failing config (e.g. the
    tunnel dropping mid-run) must not stop the later lines — above all
    the HEADLINE, which is always the last line emitted.

    Record-survival layers (round 3 lost the official record to the one
    shape none of the round-2 layers covered: the driver's outer timeout
    killed the process mid-probe-retry with nothing yet on stdout —
    BENCH_r03.json `parsed: null`, rc=124):
      1. a PROVISIONAL sentinel line is flushed at process start, so
         stdout parses no matter when or how the process dies (even
         SIGKILL);
      2. a SIGTERM handler emits the held measured headline (or the
         sentinel) before exiting — `timeout` sends TERM before KILL;
      3. the watchdog thread covers deaths the handler can't see (main
         thread wedged inside a C++ call that never returns);
      4. the probe-retry budget gives up well before the driver's
         observed ~23-25-min kill (RETRY_BUDGET_S note above).
    The driver records the LAST JSON line on stdout (BENCH_r01 and
    BENCH_r02 both parsed the final line), so any real line supersedes
    the provisional sentinel.
    """
    from distributedtensorflowexample_tpu.data.device_dataset import (
        DEQUANT_IMPLS)
    if DEQUANT not in DEQUANT_IMPLS:
        # argparse never validates a DEFAULT against choices, so a typo'd
        # BENCH_DEQUANT would otherwise surface only as per-workload
        # errors that zero the whole round's record.
        raise SystemExit(f"BENCH_DEQUANT={DEQUANT!r} is not one of "
                         f"{DEQUANT_IMPLS}")
    errors: dict = {}
    # The headline is measured FIRST but emitted LAST (see the workload
    # section); between those two points the finished line lives here so
    # a watchdog fire / SIGTERM during a later side workload emits the
    # REAL measured headline instead of discarding it for the sentinel.
    held_headline: dict = {}
    attempts: list = []
    # Exactly-once guard on the final headline emission: the normal
    # path, the watchdog thread, and the SIGTERM handler can race on a
    # kill at the wrong instant; the first wins, the rest no-op.  RLock,
    # not Lock: the SIGTERM handler runs in the MAIN thread and may
    # interrupt main() while it already holds the guard — a plain Lock
    # would self-deadlock.
    final_guard = threading.RLock()
    final_done = [False]

    def emit_unavailable(why: str, attempts_: list,
                         errors_: dict | None = None,
                         provisional: bool = False) -> None:
        # Sentinel, NOT a measurement: unit "unavailable" + value 0.0 so
        # no consumer can mistake the line for a measured 100% regression
        # (round 2's 0.0 steps/sec/chip line read exactly that way).
        detail = {"error": why[:500], "probe_attempts": attempts_[-8:],
                  "see": "OUTAGE_r05.md (continuous outage spanning "
                         "rounds 3-5), BENCH_early_r03.json (round-3 "
                         "early capture), BENCH_manual_r02.json (full "
                         "on-chip run, 2026-07-30), and BASELINE.md"}
        if provisional:
            detail["provisional"] = True
        if errors_:
            # Attached structurally (not serialized into a truncated
            # string) so the headline sweep's own per-point errors — the
            # LAST dict entries — can't be cut off by earlier workloads'.
            # list() snapshots first: the watchdog thread may serialize
            # while the main thread is still appending.
            detail["errors"] = {k: v[:300] for k, v in list(errors_.items())}
        _println(json.dumps({
            "metric": "mnist_cnn_sync_steps_per_sec_per_chip",
            "value": 0.0, "unit": "unavailable", "vs_baseline": 0.0,
            "detail": detail,
        }))

    def final_once(fn) -> None:
        with final_guard:
            if final_done[0]:
                return
            fn()
            if not _EMIT_RAW:
                sys.stdout.flush()
            # Marked done AFTER fn(): if a SIGTERM lands between the
            # mark and the print, the handler would see done, no-op, and
            # os._exit with NO final line ever emitted.  The cost is the
            # opposite rare race — an interrupt mid-print re-enters and
            # emits a second line — which is benign: the handler first
            # prints a newline to terminate any torn partial line, so
            # the driver's last-line parse always sees its complete
            # JSON.
            final_done[0] = True

    def fire_final(tag: str, why: str) -> None:
        """The line that must survive an abnormal death: the held
        measured headline if one exists (a wedged or killed side
        workload must not discard a finished contract metric), else the
        explicit sentinel."""
        if held_headline:
            detail = dict(held_headline["detail"])
            detail["errors"] = {k: v[:300] for k, v in list(errors.items())}
            detail[tag] = why
            _emit("mnist_cnn_sync_steps_per_sec_per_chip",
                  held_headline["per_chip"], _load_baselines(), detail)
        else:
            emit_unavailable(why, attempts, errors)

    # (VERDICT r3 #1a) Provisional record from the first instant, before
    # any backend touch.  This line loses to ANY later line; it is what
    # the driver reads only when the process died before producing
    # anything better.
    emit_unavailable(
        "provisional: bench.py started and was killed before it could "
        "emit a real record (probe outcomes and measurements supersede "
        "this line)", attempts, provisional=True)

    t_start = time.time()

    def on_sigterm(signum, frame):
        # (VERDICT r3 #1b) The driver's outer `timeout` sends SIGTERM
        # before SIGKILL; round 3 died sleeping in the probe-retry loop.
        # CPython delivers signals in the main thread between bytecodes —
        # time.sleep / subprocess waits return early — so this covers
        # every non-wedged kill; the watchdog covers the wedged ones.
        # os._exit: the process is being killed anyway, skip atexit.
        # Every write here goes through os.write (_EMIT_RAW): a print()
        # would raise "reentrant call" RuntimeError if the signal landed
        # while the main thread was mid-print, and that exception would
        # escape the handler and skip both the record and the exit code.
        # The try/finally makes os._exit(143) unconditional regardless.
        global _EMIT_RAW
        _EMIT_RAW = True
        try:
            # Serialize on final_guard BEFORE touching fd 1: the watchdog
            # thread emits its final record while holding it, and a raw
            # newline written between that print's flush chunks would
            # tear ITS record (the buffer lock the old print() serialized
            # on is exactly what os.write bypasses).  BOUNDED acquire,
            # not `with`: if the signal interrupted main() mid-print, the
            # watchdog can be wedged inside final_once's print() waiting
            # on the buffer lock the interrupted main thread holds — it
            # will never release the guard, and an unbounded wait here
            # would hang past the -k SIGKILL with no record and no exit
            # code.  On timeout we proceed anyway: a wedged watchdog's
            # record can never fully reach the fd, so terminating
            # whatever partial bytes it auto-flushed and writing our own
            # complete line is the best obtainable stdout.  (RLock: main-
            # thread re-entry mid-emit still succeeds immediately and
            # re-emits a complete line — the benign documented race.)
            got = final_guard.acquire(timeout=5)
            try:
                # Leading newline: if the signal interrupted main()
                # mid-print, the physical line is torn ('{...partial') —
                # without a terminator the handler's JSON would
                # concatenate onto it and the driver's last-line parse
                # would see invalid JSON.  A blank line is harmless to a
                # line-based parser.
                os.write(1, b"\n")
                if _PROBE_PROC is not None:
                    attempts.append("probe still in flight at sigterm "
                                    "(no verdict on backend state)")
                emit = lambda: fire_final(
                    "sigterm",
                    f"sigterm at t+{time.time() - t_start:.0f}s: killed "
                    "by the outer harness; lines above this one are valid "
                    "completed measurements")
                if got:
                    final_once(emit)   # re-entrant acquire: instant
                else:
                    # Guard wedged (see above): final_once would block on
                    # it forever.  Emit unguarded — exactly-once is moot
                    # when the only other holder can never finish, and a
                    # duplicate complete last line is harmless.
                    emit()
            finally:
                if got:
                    final_guard.release()
            proc = _PROBE_PROC
            if proc is not None:
                # Don't orphan a probe child wedged in axon init (it
                # would outlive us holding tunnel state).  TERM only — no
                # time for the usual grace period under the -k window.
                try:
                    proc.terminate()
                except Exception:
                    pass
            # Flight postmortem before os._exit (which skips atexit).
            # No-op unless a recorder was installed (supervised runs);
            # guarded — the record on fd 1 above is already out, and a
            # telemetry failure must not change the exit code.
            try:
                from distributedtensorflowexample_tpu.obs.recorder import (
                    dump_global)
                dump_global("sigterm")
            except Exception:
                pass
        finally:
            os._exit(143)

    # signal.signal only works from the main thread; tests that call
    # main() from a worker thread just skip the handler layer.  This is
    # deliberately NOT utils.signals.installed_signal_handler: importing
    # ANY package module pulls in jax, and the whole point of the block
    # below is that the handler is live BEFORE the first package import.
    # Keep the restore semantics in sync with that helper.
    install = threading.current_thread() is threading.main_thread()
    prev_term = signal.signal(signal.SIGTERM, on_sigterm) if install else None
    try:
        # Package import AFTER the provisional emit and handler install:
        # it can block for seconds (plugin/module import on a loaded
        # host), and a kill during it must still find a parseable stdout.
        from distributedtensorflowexample_tpu.parallel import make_mesh
        _main_run(make_mesh, errors, held_headline, attempts,
                  emit_unavailable, final_once, fire_final)
    finally:
        # Restore so one main() call inside a larger process (pytest)
        # doesn't permanently hijack that process's SIGTERM semantics.
        # A non-Python-installed previous handler reads back as None,
        # which signal.signal refuses — restore SIG_DFL then.
        if install:
            signal.signal(signal.SIGTERM,
                          prev_term if prev_term is not None
                          else signal.SIG_DFL)
    # Normal completion closes the ledger row rc=0; every other exit
    # (SIGTERM, watchdog os._exit, crash) leaves it to atexit/rc=None —
    # "unreported" is exactly what those deaths are.
    from distributedtensorflowexample_tpu.obs import ledger as obs_ledger
    obs_ledger.end_global(rc=0)


def _main_run(make_mesh, errors: dict, held_headline: dict, attempts: list,
              emit_unavailable, final_once, fire_final) -> None:
    # Supervised runs (and OBS_FLIGHT=1 opt-ins) leave a
    # flight_<pid>.json postmortem (measure/probe spans + registry)
    # next to the capture journal; sigterm=False — the record-survival
    # handler in main() owns SIGTERM and dumps the flight itself before
    # os._exit (atexit never runs on that path).
    from distributedtensorflowexample_tpu.obs import (
        recorder as obs_recorder)
    obs_recorder.maybe_install(sigterm=False)
    # Run ledger + live scrape (both env-gated, stdlib-only): the bench
    # trajectory's per-run bookkeeping lands in RUNS.jsonl (OBS_LEDGER)
    # and a mid-sweep scrape of /metrics answers on OBS_HTTP_PORT.
    from distributedtensorflowexample_tpu.obs import ledger as obs_ledger
    from distributedtensorflowexample_tpu.obs import serve as obs_serve
    obs_ledger.maybe_begin(
        "bench", config={"headline_only": HEADLINE_ONLY,
                         "dequant": DEQUANT, "repeats": REPEATS})
    obs_serve.maybe_start()
    reachable, _ = _wait_for_backend(into=attempts)
    if not reachable:
        final_once(lambda: emit_unavailable(
            "TPU backend unreachable after probe retries "
            f"(budget {RETRY_BUDGET_S:.0f}s)", attempts))
        # note= so the ledger can tell a sentinel run from a real
        # sweep (end is idempotent; main()'s bare rc=0 then no-ops).
        obs_ledger.end_global(rc=0, note="backend unreachable sentinel")
        return

    def fire_watchdog():
        final_once(lambda: fire_final(
            "watchdog",
            f"watchdog: measurement phase exceeded {TOTAL_BUDGET_S:.0f}s"
            " — a call blocked without raising (backend presumed lost "
            "mid-run); any lines above are valid completed measurements"))

    # Armed BEFORE the in-process init: make_mesh is the next backend
    # touch and itself blocks 25-45 min if the backend died after the
    # probe succeeded.  Disarmed immediately before the headline emit.
    # If it fires, the headline (measured, or the sentinel) IS the last
    # line (per-workload lines already printed stay valid — each was
    # flushed as it completed).
    # (ADVICE r3) Not armed when pinned to the CPU platform: a virtual-
    # mesh run cannot wedge on the tunnel but can legitimately exceed
    # the budget (the 8-device opt-in e2e was observed at 77+ min).
    # Platform check only — a real TPU run with BENCH_SKIP_PROBE=1 still
    # needs the watchdog.  Tests force arming via BENCH_FORCE_WATCHDOG=1.
    if _cpu_platform() and os.environ.get("BENCH_FORCE_WATCHDOG") != "1":
        watchdog_done = threading.Event()
    else:
        watchdog_done = _arm_watchdog(TOTAL_BUDGET_S, fire_watchdog)
    try:
        mesh = make_mesh()
    except Exception as e:
        watchdog_done.set()
        final_once(lambda: emit_unavailable(
            f"TPU backend unavailable: {e!r}", attempts))
        obs_ledger.end_global(rc=0, note="backend-unavailable sentinel")
        return
    num_chips = mesh.size
    baselines = _load_baselines()

    def attempt(name, fn):
        try:
            fn()
        except Exception as e:
            errors[name] = repr(e)
            traceback.print_exc()

    def attach_roofline(detail, best, name, batch_per_chip, **roofline_kw):
        """Same-window pure-compute probe + measured/roofline ratio —
        the ONE definition of the ratio (max of probe repeats), shared by
        every line that carries it."""
        roof: list = []
        cost: dict = {}
        attempt(name, lambda: roof.extend(
            _roofline_probe(mesh, batch_per_chip, cost_out=cost,
                            **roofline_kw)))
        if roof:
            detail["roofline_probe"] = roof
            detail["vs_roofline"] = round(best / max(roof), 4)
        if cost:
            detail["roofline_cost_per_step"] = cost
            # With the measured step's cost also present, the bytes
            # ratio bounds the bandwidth-bound share of the vs_roofline
            # gap in the SAME window (VERDICT r3 #5: softmax's 0.68 had
            # no attribution) — if measured/roofline rate ≈ roofline/
            # measured bytes, the gap is the gather/ring/augment traffic
            # the probe deliberately lacks, not dispatch.
            mcost = detail.get("cost_per_step") or {}
            if mcost.get("bytes_accessed") and cost.get("bytes_accessed"):
                detail["roofline_bytes_ratio"] = round(
                    cost["bytes_accessed"] / mcost["bytes_accessed"], 4)

    def run_simple(metric, model, dataset, batch_per_chip, unroll, steps,
                   extra_detail=None, roofline_kw=None, attach_cost=False,
                   **make_kw):
        """Build + measure one workload and emit its line (the shape every
        non-headline config shares).  ``roofline_kw`` adds a same-window
        pure-compute probe + measured/roofline ratio so the line stays
        interpretable under the shared chip's cross-window variance;
        ``attach_cost`` adds the measured step's per-step flops/bytes so
        the vs_roofline gap carries its own bandwidth attribution."""
        step, ds, state, u = _make(model, dataset, batch_per_chip, unroll,
                                   mesh, dequant_impl=DEQUANT, **make_kw)
        cost: dict = {}
        if attach_cost:
            # peek, not next: the probe must not advance the ring.
            attempt(f"cost_{metric}", lambda: cost.update(
                _cost_per_step(step, state, ds.peek(), u)))
        best, rates, _ = _measure(step, ds, state, steps, u)
        detail = {"repeats": rates, "unroll": u,
                  "batch_per_chip": batch_per_chip,
                  "dequant": ds.dequant_impl or "none",
                  **(extra_detail or {})}
        if cost:
            detail["cost_per_step"] = cost
        if roofline_kw is not None:
            attach_roofline(detail, best, f"roofline_{metric}",
                            batch_per_chip, **roofline_kw)
        _emit(metric, best / num_chips, baselines, detail)

    def config4():
        # Round-2 measured ~43 ms/call dispatch through the degraded
        # tunnel; at unroll 8 that dispatch alone caps ResNet at ~186
        # steps/s, so the number said nothing about compute.  Sweep up to
        # a full epoch per call (spe = 195 at batch 256).
        b_rn = BATCH["resnet"]
        spe_cifar = TRAIN_N["cifar10"] // (b_rn * num_chips)
        flops_box: list = []   # at-most-once cost probe across sweep points
        rn_dequant: dict = {}  # impl the built dataset actually resolved

        def mk(unroll):
            step, ds, state, u = _make("resnet20", "cifar10", b_rn, unroll,
                                       mesh, augment="cifar", lr=0.1,
                                       dequant_impl=DEQUANT)
            rn_dequant["dequant"] = ds.dequant_impl or "none"
            if not flops_box:
                # peek, not next: the probe must not advance the ring ahead
                # of state.step, or a later window would read an evicted
                # perm row.
                flops_box.append(_flops_per_step(step, state, ds.peek(), u))
            return step, ds, state, u

        best_overall, best_unroll, best_rates, sweep = _sweep(
            RESNET_UNROLLS(spe_cifar), mk,
            lambda u: max(MIN_STEPS["resnet"], 2 * u),
            "resnet_sweep_", errors)
        if best_unroll is None:
            # Every point failed: emit nothing (a 0.0 line would read as a
            # silent 100% regression); the errors ride the headline line.
            return
        flops = flops_box[0] if flops_box else None
        per_chip = best_overall / num_chips
        # flops is whole-module (all devices); MFU = F*S_global/(N*peak)
        # = F*per_chip/peak.
        mfu = (flops * per_chip / PEAK_FLOPS) if flops else None
        # Same-window pure-compute roofline (scanned fixed batch, NO
        # augment/gather): the measured/roofline gap is the input+augment+
        # dispatch share — the attribution the MFU number alone can't give.
        detail = {"repeats": best_rates, "best_unroll": best_unroll,
                  "unroll_sweep": sweep, "batch_per_chip": b_rn,
                  "dequant": rn_dequant.get("dequant", "none"),
                  "flops_per_step": flops,
                  "mfu": round(mfu, 4) if mfu is not None else None}
        attach_roofline(detail, best_overall, "roofline_resnet", b_rn,
                        length=ROOFLINE_LEN["resnet"], model_name="resnet20",
                        sample=(32, 32, 3), lr=0.1)
        _emit("cifar_resnet20_steps_per_sec_per_chip", per_chip, baselines,
              detail)

    # Multi-epoch fused windows everywhere (the perm ring removed the
    # per-epoch unroll ceiling): softmax steps are ~10x shorter than CNN
    # steps so they need the deepest fusion; the kernel variants use the
    # same unroll as the headline sweep's 4-epoch point so their deltas
    # read directly against sweep["936"] (single-chip).
    b_cnn, b_sm = BATCH["cnn"], BATCH["softmax"]
    spe = TRAIN_N["mnist"] // (b_cnn * num_chips)
    # Softmax steps are ~10x shorter than CNN steps, so dispatch still
    # shows at unroll 2048 (~3.4 epochs); fuse 16 epochs per call like the
    # headline sweep's deepest point.
    spe_softmax = TRAIN_N["mnist"] // (b_sm * num_chips)
    with mesh:
        # --- config 3 HEADLINE: MNIST CNN sync, unroll sweep -------------
        # Measured FIRST, emitted LAST.  Round 3 measured a recovery
        # window of ~9 minutes between two outage stretches: a run that
        # saves the contract metric for the end captures side workloads
        # and loses the headline when the window closes mid-run.  So the
        # likely-best sweep point (deepest unroll — it won every recorded
        # sweep) runs first, its same-window roofline immediately after
        # (the vs_roofline ratio is the one number that survives chip-
        # sharing variance — it must come from the SAME window as the
        # measurement it calibrates), then the remaining sweep points;
        # the emit order (headline last) is preserved by holding the
        # finished line until the end.
        # Multi-epoch fused windows (the perm ring, data/device_dataset.py)
        # let the unroll go past an epoch: sweep up to 16 epochs per call
        # (even 43 ms/call of degraded-tunnel dispatch amortizes to <3%).
        dequant_box: dict = {}   # impl the built headline dataset resolved

        def mk_headline(unroll):
            step, ds, state, u = _make("mnist_cnn", "mnist", b_cnn, unroll,
                                       mesh, dequant_impl=DEQUANT)
            dequant_box["dequant"] = ds.dequant_impl or "none"
            return step, ds, state, u

        steps_for = lambda u: max(MIN_STEPS["headline"], u * 4)
        best_overall, best_unroll, best_rates, sweep = _sweep(
            {16 * spe}, mk_headline, steps_for, "sweep_", errors)
        headline_detail = {"repeats": best_rates, "best_unroll": best_unroll,
                           "unroll_sweep": sweep, "batch_per_chip": b_cnn}
        if HEADLINE_ONLY:
            # Readable provenance: this run deliberately measured only
            # the contract metric (capture phase 1), not a thin window.
            headline_detail["headline_only"] = True

        def hold_best(b, u, r):
            """Record (b, u, r) as the held headline.  From the first
            call on, a watchdog fire emits THIS measured line, not the
            sentinel (a wedged side workload must not discard a finished
            contract metric).  The roofline is RE-probed on every call:
            the ratio only means something when probe and measurement
            share a window, so a promoted later point must not inherit
            the first point's probe — and the stale keys are dropped
            first so a failed re-probe can't leave a cross-window ratio
            behind."""
            nonlocal best_overall, best_unroll, best_rates
            best_overall, best_unroll, best_rates = b, u, r
            headline_detail["repeats"] = r
            headline_detail["best_unroll"] = u
            if "dequant" in dequant_box:
                # Attestation travels WITH the held line: whichever path
                # (normal emit, watchdog, sigterm) flushes the headline,
                # the record names the dequant kernel that produced it.
                headline_detail["dequant"] = dequant_box["dequant"]
            headline_detail.pop("roofline_probe", None)
            headline_detail.pop("vs_roofline", None)
            # (ADVICE r3 medium) Held BEFORE the roofline probe: the
            # probe is a backend-touching jit call — the exact round-3
            # wedge shape — and a watchdog/SIGTERM fire during it must
            # emit the measurement it calibrates, not the sentinel.  The
            # held detail is the SAME dict, so the ratio merges in the
            # moment the probe completes.
            held_headline["per_chip"] = b / num_chips
            held_headline["detail"] = headline_detail
            attach_roofline(headline_detail, b, "roofline", b_cnn,
                            length=ROOFLINE_LEN["headline"])

        if best_unroll is not None:
            hold_best(best_overall, best_unroll, best_rates)

        if not HEADLINE_ONLY:
            # Remaining sweep points (still before the side workloads);
            # a later point that beats — or replaces a failed — first
            # point is promoted into the held line.
            b2, u2, r2, s2 = _sweep(HEADLINE_REST_UNROLLS(spe), mk_headline,
                                    steps_for, "sweep_", errors)
            sweep.update(s2)   # same dict as headline_detail["unroll_sweep"]
            if u2 is not None and b2 > best_overall:
                hold_best(b2, u2, r2)

            def dequant_ab():
                """tools/ab_quantize.py's sweep, promoted into the
                official record (round-5 satellite): measure each
                ALTERNATIVE dequant impl in the exact headline config at
                the winning unroll — the resolved default's own rate IS
                the held headline — and auto-select the fastest into the
                held line.  One call per repeat (not steps_for): each
                point exists to attest the impl ordering in THIS window
                (AB_quantize_r05 measured 4.1x between impls), not to
                re-derive the headline."""
                base = dequant_box.get("dequant", "affine")
                ab: dict = {}
                promote = None
                for impl in DEQUANT_AB_IMPLS:
                    if impl == base:
                        continue
                    try:
                        step, ds, state, u = _make(
                            "mnist_cnn", "mnist", b_cnn, best_unroll, mesh,
                            dequant_impl=impl)
                        ran = ds.dequant_impl or impl
                        b, rates, state = _measure(
                            step, ds, state,
                            max(MIN_STEPS["headline"], u), u)
                        ab[ran] = rates
                        if b > best_overall and (
                                promote is None or b > promote[1]):
                            promote = (ran, b, u, step, ds, state)
                    except Exception as e:
                        errors[f"dequant_ab_{impl}"] = repr(e)
                        traceback.print_exc()
                headline_detail["dequant_ab"] = ab
                if promote is not None:
                    # A winner supersedes the resolved default — but only
                    # after CONFIRMING at the headline's own methodology
                    # (steps_for(u) per repeat): the thin A/B points time
                    # one call per repeat, so their best-of-repeats is
                    # noisier and upward-biased under max(), and a lucky
                    # scheduling window must not rename the official
                    # record to a kernel that is not actually fastest.
                    ran, _b_thin, u, step, ds, state = promote
                    try:
                        b2, r2, _ = _measure(step, ds, state,
                                             steps_for(u), u)
                        if b2 > best_overall:
                            dequant_box["dequant"] = ran
                            hold_best(b2, u, r2)
                    except Exception as e:
                        errors["dequant_ab_confirm"] = repr(e)
                        traceback.print_exc()

            if (DEQUANT == "auto" and best_unroll is not None
                    and dequant_box.get("dequant") != "none"):
                # The "none" guard: an unquantized headline split
                # (recorded dequant == "none") has no dequant kernel to
                # A/B — every "alternative" would run the identical
                # float-resident path and the record would attest a
                # comparison that never happened.  An ABSENT key (the
                # headline build itself failed; the held line came from
                # the sweep) still runs the A/B against the default.
                # Before the side workloads: the impl attestation decides
                # how the next window reads EVERY number in this record,
                # so it outranks the side lines if the window closes.
                attempt("dequant_ab", dequant_ab)

            # Side workloads, most valuable first (the window may close
            # any time): the flagship ResNet, the async contract config,
            # then softmax and the kernel variants.
            attempt("resnet20", config4)
            attempt("cnn_async", lambda: run_simple(
                "mnist_cnn_async_steps_per_sec_per_chip", "mnist_cnn",
                "mnist", b_cnn, 4 * spe, 8 * spe,
                extra_detail={"async_period": 8}, sync=False))
            attempt("softmax", lambda: run_simple(
                "mnist_softmax_steps_per_sec_per_chip", "softmax", "mnist",
                b_sm, 16 * spe_softmax, 32 * spe_softmax, momentum=0.0,
                lr=0.5, attach_cost=True,
                roofline_kw={"model_name": "softmax", "momentum": 0.0,
                             "lr": 0.5, "length": ROOFLINE_LEN["softmax"]}))
            attempt("pallas_ce", lambda: run_simple(
                "mnist_cnn_sync_pallas_ce_steps_per_sec_per_chip",
                "mnist_cnn", "mnist", b_cnn, 4 * spe, 8 * spe,
                ce_impl="pallas"))
            attempt("fused_sgd", lambda: run_simple(
                "mnist_cnn_sync_fused_sgd_steps_per_sec_per_chip",
                "mnist_cnn", "mnist", b_cnn, 4 * spe, 8 * spe,
                fused_opt=True))

        if best_unroll is None:
            # Every headline point failed — the backend died AFTER the
            # initial probe succeeded (mid-run outage, the round-3 03:49
            # UTC capture's exact failure shape).  A 0.0 steps/sec/chip
            # line would read as a measured 100% regression, so emit the
            # same explicit sentinel the up-front probe failure uses.
            watchdog_done.set()
            final_once(lambda: emit_unavailable(
                "every headline sweep point failed (no measurement; "
                "mid-run backend loss is the known cause of this shape, "
                "but read detail.errors for the actual per-point failures)",
                attempts, errors))
            obs_ledger.end_global(rc=0,
                                  note="all-sweep-points-failed sentinel")
            return
        if errors:   # attached last so any side-workload failure shows too
            headline_detail["errors"] = errors
        # (ADVICE r3) Disarm BEFORE the emit: a budget lapse between the
        # emit and the set() used to print a duplicate sentinel AFTER the
        # valid headline.  Disarming first loses nothing — the held line
        # guarantees a fire in that instant emits the same measured data,
        # and final_once makes the emission exactly-once either way.
        watchdog_done.set()
        final_once(lambda: _emit("mnist_cnn_sync_steps_per_sec_per_chip",
                                 best_overall / num_chips, baselines,
                                 headline_detail))


if __name__ == "__main__":
    import argparse
    _ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    from distributedtensorflowexample_tpu.data.device_dataset import (
        DEQUANT_IMPLS as _IMPLS)
    _ap.add_argument(
        "--dequant", default=DEQUANT, choices=_IMPLS,
        help="in-step dequant impl for resident splits; auto resolves the "
             "fast path per split AND A/Bs the alternatives at the winning "
             "unroll, recording the selection in the headline detail")
    _ap.add_argument(
        "--probe_timeout_s", type=float, default=PROBE_TIMEOUT_S,
        help="per-probe backend timeout (env BENCH_PROBE_TIMEOUT_S; the "
             "round-5 watch log burned exactly 300 s per probe for 215 "
             "probes — shorter probes + the jittered retry backoff sample "
             "an outage's edges faster)")
    _ap.add_argument(
        "--retry_interval_s", type=float, default=RETRY_INTERVAL_S,
        help="mean pause between failed probes (env BENCH_RETRY_INTERVAL_S"
             "; actual sleeps are jittered +/-25%% to decorrelate "
             "fleet-wide retry bursts against the shared tunnel)")
    _args = _ap.parse_args()
    DEQUANT = _args.dequant
    PROBE_TIMEOUT_S = _args.probe_timeout_s
    RETRY_INTERVAL_S = _args.retry_interval_s
    main()
