"""Benchmark harness — emits ONE JSON line with the headline metric.

Headline (BASELINE.json "metric"): MNIST steps/sec/chip, sync-SGD.
The reference published no numbers (BASELINE.json "published": {}), so
``vs_baseline`` is computed against this repo's own recorded baseline in
``BASELINE_SELF.json`` when present, else 1.0.  The recorded baseline is
this round's first measurement (host-fed pipeline, 590.8 steps/s/chip on
one v5e chip) — the number the device-resident input path was built to
beat.

Runs the real trainer stack: the dataset resident in HBM, batches
gathered on device, the jitted sync-SGD step (parallel/sync.py) — the
driver invokes this on a real TPU chip.  Exits cleanly (no hard kill
needed): small fixed step counts.  The chip is reached through a shared
tunnel with visible noisy-neighbor variance, so the measured window is
the best of a few short repeats (steady-state rate, not a lucky queue
flush — each repeat blocks on its own final metrics).
"""

from __future__ import annotations

import json
import os
import time

import jax

WARMUP_STEPS = 32
MEASURE_STEPS = 320
REPEATS = 3
BATCH_PER_CHIP = 256
UNROLL = 16           # SGD steps fused per compiled call (lax.scan)


def main() -> None:
    import optax

    from distributedtensorflowexample_tpu.data import DeviceDataset
    from distributedtensorflowexample_tpu.data.mnist import load_mnist
    from distributedtensorflowexample_tpu.models import build_model
    from distributedtensorflowexample_tpu.parallel import (
        make_mesh, replicated_sharding)
    from distributedtensorflowexample_tpu.parallel.sync import (
        make_indexed_train_step)
    from distributedtensorflowexample_tpu.training.state import TrainState

    mesh = make_mesh()
    num_chips = mesh.size
    global_batch = BATCH_PER_CHIP * num_chips

    train_x, train_y = load_mnist("/tmp/data", "train")
    ds = DeviceDataset(train_x, train_y, global_batch, mesh=mesh, seed=0,
                       steps_per_next=UNROLL)

    model = build_model("mnist_cnn", dropout=0.5)
    state = TrainState.create_sharded(
        model, optax.sgd(0.05, momentum=0.9),
        (global_batch, 28, 28, 1), 0, replicated_sharding(mesh))
    step = make_indexed_train_step(global_batch, ds.steps_per_epoch,
                                   mesh=mesh, unroll_steps=UNROLL)

    best = 0.0
    with mesh:
        for _ in range(WARMUP_STEPS // UNROLL):
            state, metrics = step(state, next(ds))
        jax.block_until_ready(metrics)

        for _ in range(REPEATS):
            t0 = time.perf_counter()
            for _ in range(MEASURE_STEPS // UNROLL):
                state, metrics = step(state, next(ds))
            jax.block_until_ready(metrics)
            best = max(best, MEASURE_STEPS / (time.perf_counter() - t0))

    per_chip = best / num_chips

    baseline = None
    if os.path.exists("BASELINE_SELF.json"):
        try:
            with open("BASELINE_SELF.json") as f:
                baseline = json.load(f).get("mnist_cnn_steps_per_sec_per_chip")
        except (json.JSONDecodeError, OSError):
            baseline = None
    vs_baseline = round(per_chip / baseline, 4) if baseline else 1.0

    print(json.dumps({
        "metric": "mnist_cnn_sync_steps_per_sec_per_chip",
        "value": round(per_chip, 2),
        "unit": "steps/sec/chip",
        "vs_baseline": vs_baseline,
    }))


if __name__ == "__main__":
    main()
