"""Benchmark harness — emits ONE JSON line with the headline metric.

Headline (BASELINE.json "metric"): MNIST steps/sec/chip, sync-SGD.
The reference published no numbers (BASELINE.json "published": {}), so
``vs_baseline`` is computed against this repo's own recorded baseline in
``BASELINE_SELF.json`` when present (written by earlier rounds), else 1.0.

Runs the real trainer stack (jitted sync step, device prefetch) on the
default platform — the driver invokes this on a real TPU chip.  Exits
cleanly (no hard kill needed): small fixed step counts.
"""

from __future__ import annotations

import json
import os
import time

import jax

WARMUP_STEPS = 20
MEASURE_STEPS = 200
BATCH_PER_CHIP = 256


def main() -> None:
    import optax

    from distributedtensorflowexample_tpu.data import Batcher, DevicePrefetcher
    from distributedtensorflowexample_tpu.data.mnist import load_mnist
    from distributedtensorflowexample_tpu.models import build_model
    from distributedtensorflowexample_tpu.parallel import (
        batch_sharding, make_mesh, replicated_sharding)
    from distributedtensorflowexample_tpu.parallel.sync import make_train_step
    from distributedtensorflowexample_tpu.training.state import TrainState

    mesh = make_mesh()
    num_chips = mesh.size
    global_batch = BATCH_PER_CHIP * num_chips

    train_x, train_y = load_mnist("/tmp/data", "train")
    batcher = Batcher(train_x, train_y, global_batch, seed=0)
    batches = DevicePrefetcher(batcher, sharding=batch_sharding(mesh), depth=2)

    model = build_model("mnist_cnn", dropout=0.5)
    state = TrainState.create_sharded(
        model, optax.sgd(0.05, momentum=0.9),
        (global_batch, 28, 28, 1), 0, replicated_sharding(mesh))
    step = make_train_step()

    with mesh:
        for _ in range(WARMUP_STEPS):
            state, metrics = step(state, next(batches))
        jax.block_until_ready(metrics)

        t0 = time.perf_counter()
        for _ in range(MEASURE_STEPS):
            state, metrics = step(state, next(batches))
        jax.block_until_ready(metrics)
        dt = time.perf_counter() - t0

    steps_per_sec = MEASURE_STEPS / dt
    per_chip = steps_per_sec / num_chips

    baseline = None
    if os.path.exists("BASELINE_SELF.json"):
        try:
            with open("BASELINE_SELF.json") as f:
                baseline = json.load(f).get("mnist_cnn_steps_per_sec_per_chip")
        except (json.JSONDecodeError, OSError):
            baseline = None
    vs_baseline = round(per_chip / baseline, 4) if baseline else 1.0

    print(json.dumps({
        "metric": "mnist_cnn_sync_steps_per_sec_per_chip",
        "value": round(per_chip, 2),
        "unit": "steps/sec/chip",
        "vs_baseline": vs_baseline,
    }))


if __name__ == "__main__":
    main()
