#!/usr/bin/env python
"""graft-LM bench family — tokens/sec, MFU, bytes/roofline, and the knob
A/B matrix at the scale where the knobs bind (ROADMAP direction #5).

Three instruments on one workload (models/transformer_lm.py):

1. **Throughput + MFU** (``--throughput_size``, default lm_small): the
   measured tokens/sec line, plus the PR-2 bytes-audit/roofline fields
   and the new MFU line — numerator = measured steps/sec x the
   dot-general/attention FLOP audit (utils/profiling.flops_audit, the
   golden-pinned MFU denominator), never the aggregate cost_analysis
   flops (which lumps in elementwise noise).
2. **Knob A/B matrix** (``--size``, default lm_base ~57M params): the
   remat/shard_update/bucket_grads/zero3 matrix re-run where
   arXiv:2004.13336 actually evaluates — optimizer state + activations
   in the hundreds of MB — with MEASURED wins: per-device
   param+grad+opt residency read from the live array shardings for
   EVERY config (``utils/profiling.state_residency_per_device`` —
   ZeRO-1's opt-only 1/D and ZeRO-3's param+opt 1/D against ~458 MB of
   replicated params+momentum) and per-device peak temp/activation
   bytes from the compiler's own memory analysis (remat's
   resident-activation diet; where ZeRO-3's transient gathered params
   and the 1/D gradient rows live).  The ``zero3`` /
   ``zero3_nooverlap`` pair times the double-buffered AG-prefetch
   schedule against the serial-gather control (pure scheduling —
   bitwise-same math; on the CPU platform the pair only proves both
   schedules compile and run, the overlap win is the armed TPU
   prediction).
3. **Collective inventory** per config (the PR-6 instrument): the
   compiled schedule each knob actually emits.

Default mode forces a multi-device CPU mesh (bench_collectives.py's
in-process route) so every number is driver-measurable today; ``--real``
is the capture-window phase (tools/supervise.py --capture, phase
``lm``): probes with the bench.py env knobs, emits a sentinel when the
backend is down, and self-labels ``platform`` so CPU numbers are never
mistakable for chip numbers.  MFU is quoted against TPU_PEAK_FLOPS
(bench.PEAK_FLOPS, v5e bf16 default) like bench_profile.py — on the CPU
platform the ratio is only the armed prediction's denominator, and the
record says so.

Output: JSON lines (bench.py dialect) + ``--json`` writes the full
BENCH_lm_* artifact.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
import traceback

_ALL_KNOBS = ("base", "remat", "shard_update", "bucket", "zero1",
              "zero3", "zero3_nooverlap")


def _emit(metric: str, value: float, unit: str, detail: dict,
          lines: list) -> None:
    # 10 decimals: a CPU-platform MFU quoted against TPU peak is ~1e-8
    # and must survive rounding (the armed prediction divides by it).
    rec = {"metric": metric, "value": round(float(value), 10),
           "unit": unit, "vs_baseline": 1.0, "detail": detail}
    print(json.dumps(rec), flush=True)
    lines.append(rec)


def _sentinel(args, attempts: list) -> None:
    line = {"metric": "lm_tokens_per_sec_per_chip", "value": 0.0,
            "unit": "unavailable", "vs_baseline": 0.0,
            "detail": {"error": "backend unreachable — sentinel record; "
                                "probe outcomes supersede this line",
                       "probe_attempts": attempts, "provisional": True}}
    print(json.dumps(line), flush=True)
    if args.json:
        with open(args.json, "w") as f:
            json.dump(line, f, indent=1)


def optstate_bytes_per_device(opt_state) -> int:
    """Per-device bytes of the optimizer state, read from the LIVE array
    shardings (one addressable shard per leaf): the measured form of the
    ZeRO-1 1/D claim — a replicated leaf's shard is the whole leaf, a
    row-sharded leaf's shard is its 1/D block."""
    import jax
    import numpy as np

    total = 0
    for leaf in jax.tree.leaves(opt_state):
        if not hasattr(leaf, "addressable_shards"):
            continue
        shard = leaf.addressable_shards[0]
        total += int(np.prod(shard.data.shape)) * leaf.dtype.itemsize
    return total


def _build(size: str, mesh, batch_per_chip: int, seq_len: int,
           unroll: int, *, remat: str = "none", shard_update: bool = False,
           bucket: bool = False, shard_params: bool = False,
           overlap: bool = True, seed: int = 0,
           split_n: int | None = None):
    """One knob config as an Engine declaration (engine/engine.py): the
    Engine resolves the remat/shard_update/bucket_grads/shard_params
    knobs into the SAME builders and layout passes run_training wires,
    so the bench measures the trainer's programs.  input_fn pins the
    bench's deterministic split sizing; optimizer_fn pins the bare
    float-LR optax.sgd (a schedule-wrapped twin has a DIFFERENT
    opt_state pytree — the measured program must stay the trainer's,
    bitwise)."""
    from distributedtensorflowexample_tpu.config import RunConfig
    from distributedtensorflowexample_tpu.engine import Engine, RunSpec
    from distributedtensorflowexample_tpu.parallel.bucketing import (
        DEFAULT_BUCKET_BYTES)

    D = mesh.size
    global_batch = batch_per_chip * D
    n = split_n if split_n is not None else max(global_batch * 8, 256)

    def input_fn(cfg, split):
        from distributedtensorflowexample_tpu.data.lm import load_lm
        return load_lm("", split, seed=seed, num=n, seq_len=seq_len)

    def optimizer_fn(cfg, _mesh, wrap_shard_update):
        import optax
        tx = optax.sgd(0.1, momentum=0.9)
        if cfg.shard_update and wrap_shard_update:
            from distributedtensorflowexample_tpu.training.optimizers \
                import cross_replica_update_sharding
            tx = cross_replica_update_sharding(tx, _mesh)
        return tx

    cfg = RunConfig(batch_size=batch_per_chip, seed=seed, remat=remat,
                    shard_update=shard_update,
                    bucket_grads=str(DEFAULT_BUCKET_BYTES) if bucket else "",
                    shard_params=shard_params, zero3_overlap=overlap,
                    learning_rate=0.1, momentum=0.9, dropout=0.0)
    spec = RunSpec(model=size, dataset="lm", config=cfg,
                   input_fn=input_fn, optimizer_fn=optimizer_fn)
    built = Engine(spec).build(mesh=mesh, unroll=unroll)
    return built.step, built.ds, built.state, built.global_batch


def _measure_rate(step, ds, state, steps: int, unroll: int,
                  repeats: int) -> tuple[float, list, object]:
    import jax
    calls = max(1, steps // unroll)
    state, metrics = step(state, next(ds))       # compile + warm
    jax.block_until_ready(metrics)
    rates = []
    for _ in range(max(1, repeats)):
        t0 = time.perf_counter()
        for _ in range(calls):
            state, metrics = step(state, next(ds))
        jax.block_until_ready(metrics)
        rates.append(calls * unroll / (time.perf_counter() - t0))
    return max(rates), [round(r, 4) for r in rates], state


def _strip_collectives(inv: dict) -> dict:
    """Record-sized view of a collective inventory (drop per-op rows)."""
    if not inv:
        return {}
    return {k: v for k, v in inv.items() if k != "ops"}


def run_throughput(args, mesh, platform, lines, errors) -> None:
    import bench
    from distributedtensorflowexample_tpu.obs.anomaly import spread_fraction
    from distributedtensorflowexample_tpu.utils.profiling import (
        compiled_program_audit)

    n = mesh.size
    size = args.throughput_size
    step, ds, state, global_batch = _build(
        size, mesh, args.batch_per_chip, args.seq_len, args.unroll,
        seed=args.seed)
    audit = compiled_program_audit(step, (state, ds.peek()),
                                   unroll=args.unroll, top_k=8)
    best, rates, state = _measure_rate(step, ds, state, args.steps,
                                       args.unroll, args.repeats)
    tokens_per_step = global_batch * args.seq_len
    hbm_bw = float(os.environ.get("TPU_HBM_BW", 819e9))     # v5e bytes/s
    detail = {
        "platform": platform, "devices": n, "size": size,
        "global_batch": global_batch, "seq_len": args.seq_len,
        "unroll": args.unroll, "tokens_per_step": tokens_per_step,
        "steps_per_sec": round(best, 4),
        "repeats": rates,
        "spread_frac": round(spread_fraction(rates), 4),
        "token_storage": "uint8" if ds.token_data else "int32",
    }
    mfu_detail = dict(detail)
    fl = audit.get("flops") or {}
    cost = audit.get("cost") or {}
    if fl.get("flops_per_step"):
        # The compiled module is the PER-DEVICE SPMD program: every
        # audited number (flops, bytes, temp arena) is per device, so
        # MFU needs no further /n — per-chip work x rate over per-chip
        # peak IS the utilization.
        model_flops = fl["flops_per_step"]
        detail["model_flops_per_step_per_device"] = model_flops
        detail["model_flops_per_sec_per_device"] = round(
            model_flops * best)
        detail["cost_analysis_flops_per_step_per_device"] = \
            cost.get("flops")
        detail["flops_audit"] = fl
        mfu = model_flops * best / bench.PEAK_FLOPS
        mfu_detail.update(
            model_flops_per_step_per_device=model_flops,
            peak_flops=bench.PEAK_FLOPS,
            note=("MFU numerator = measured rate x the dot/attention "
                  "FLOP audit of the per-device program; denominator = "
                  "TPU_PEAK_FLOPS — on the cpu platform this is the "
                  "armed prediction's denominator, not a CPU "
                  "utilization"))
    else:
        mfu = 0.0
        mfu_detail["error"] = "no flops audit available"
    bz = audit.get("bytes") or {}
    if bz:
        detail["bytes_audit"] = {k: v for k, v in bz.items()
                                 if k != "top_ops"}
        nbytes_eff = bz.get("bytes_effective_per_step")
        if nbytes_eff:
            detail["bw_roofline_effective_steps_per_sec"] = round(
                hbm_bw / nbytes_eff, 2)
            if fl.get("flops_per_step"):
                detail["arith_intensity_effective"] = round(
                    fl["flops_per_step"] / nbytes_eff, 3)
    if audit.get("collectives"):
        detail["collectives"] = _strip_collectives(audit["collectives"])
    _emit(f"{size}_tokens_per_sec_per_chip", best * tokens_per_step / n,
          "tokens/sec/chip", detail, lines)
    _emit(f"{size}_mfu", mfu, "fraction of TPU_PEAK_FLOPS", mfu_detail,
          lines)


def run_ab_matrix(args, mesh, platform, lines, errors) -> None:
    from distributedtensorflowexample_tpu.obs.trace import span
    from distributedtensorflowexample_tpu.utils.profiling import (
        compiled_program_audit)

    D = mesh.size
    size = args.size
    configs = {
        "base": {},
        "remat": {"remat": "block"},
        "shard_update": {"shard_update": True},
        "bucket": {"bucket": True},
        "zero1": {"bucket": True, "shard_update": True},
        "zero3": {"bucket": True, "shard_update": True,
                  "shard_params": True},
        "zero3_nooverlap": {"bucket": True, "shard_update": True,
                            "shard_params": True, "overlap": False},
    }
    if D <= 1:
        # No cross-replica redundancy to shard and nothing to bucket on
        # one device: land the measurable remat A/B, label the rest.
        configs = {"base": {}, "remat": {"remat": "block"}}
    results: dict = {}
    for name, kw in configs.items():
        if args.knobs and name not in args.knobs:
            continue
        try:
            with span(f"lm_ab_{name}", size=size):
                step, ds, state, global_batch = _build(
                    size, mesh, args.ab_batch_per_chip, args.seq_len,
                    args.ab_unroll, seed=args.seed, **kw)
                audit = compiled_program_audit(
                    step, (state, ds.peek()), unroll=args.ab_unroll)
                entry = {
                    "config": kw,
                    "global_batch": global_batch,
                    "opt_state_bytes_per_device":
                        optstate_bytes_per_device(state.opt_state),
                    # Per-device resident param+grad+opt split for EVERY
                    # config: the zero3 A/B's measured baseline column
                    # (grads are step-local on every path — they live in
                    # memory.temp_bytes below).
                    "residency": audit.get("residency") or {},
                    "memory": audit.get("memory") or {},
                    "collectives": _strip_collectives(
                        (audit.get("collectives") or {})),
                    "model_flops_per_step_per_device":
                        (audit.get("flops") or {}).get("flops_per_step"),
                }
                if args.ab_steps > 0 and name in args.ab_timed_knobs:
                    best, rates, _ = _measure_rate(
                        step, ds, state, args.ab_steps, args.ab_unroll,
                        args.ab_repeats)
                    entry["steps_per_sec"] = round(best, 4)
                    entry["tokens_per_sec_per_chip"] = round(
                        best * global_batch * args.seq_len / D, 2)
                    entry["repeats"] = rates
                elif args.ab_steps > 0:
                    entry["timing"] = "skipped (see --ab_timed_knobs)"
                results[name] = entry
        except Exception as e:
            errors[f"ab_{name}"] = repr(e)
            traceback.print_exc()

    base = results.get("base")
    shared = {"platform": platform, "devices": D, "size": size,
              "seq_len": args.seq_len,
              "batch_per_chip": args.ab_batch_per_chip}
    if base:
        base_temp = (base["memory"] or {}).get("temp_bytes")
        base_opt = base["opt_state_bytes_per_device"]
        if "remat" in results and base_temp:
            remat_temp = (results["remat"]["memory"] or {}).get(
                "temp_bytes")
            if remat_temp:
                _emit(f"{size}_remat_activation_savings_frac",
                      1.0 - remat_temp / base_temp, "fraction",
                      {**shared,
                       "temp_bytes_base": base_temp,
                       "temp_bytes_remat": remat_temp,
                       "note": "per-device temp/activation arena from "
                               "the compiler's memory analysis; remat "
                               "recomputes block forwards instead of "
                               "keeping them resident"}, lines)
        for name, metric in (("shard_update",
                              f"{size}_shard_update_optstate_shrink_x"),
                             ("zero1",
                              f"{size}_zero1_optstate_shrink_x")):
            if name in results and base_opt:
                opt = results[name]["opt_state_bytes_per_device"]
                if opt:
                    _emit(metric, base_opt / opt, "x (1/D ideal = D)",
                          {**shared,
                           "opt_state_bytes_per_device_base": base_opt,
                           f"opt_state_bytes_per_device_{name}": opt,
                           "collectives": results[name]["collectives"]
                           .get("multiset", {})},
                          lines)
        base_res = (base.get("residency") or {}).get(
            "state_bytes_per_device")
        if "zero3" in results and base_res:
            z3 = results["zero3"]
            z3_res = (z3.get("residency") or {}).get(
                "state_bytes_per_device")
            if z3_res:
                _emit(f"{size}_zero3_state_residency_shrink_x",
                      base_res / z3_res, "x (1/D ideal = D)",
                      {**shared,
                       "state_bytes_per_device_base": base_res,
                       "state_bytes_per_device_zero3": z3_res,
                       "residency_base": base.get("residency"),
                       "residency_zero3": z3.get("residency"),
                       "temp_bytes_zero3": (z3.get("memory") or {}).get(
                           "temp_bytes"),
                       "collectives": z3["collectives"].get("multiset",
                                                            {}),
                       "note": "per-device resident params+opt from the "
                               "live donated-argument shardings (grads "
                               "are step-local on every path and live "
                               "in temp_bytes); 1/D ideal = D"}, lines)
    # Outside the `if base:` gate on purpose: the ratio needs only the
    # zero3 pair, and the armed next-window capture runs exactly
    # `--knobs zero3,zero3_nooverlap` with no base column.
    on = (results.get("zero3") or {}).get("steps_per_sec")
    off = (results.get("zero3_nooverlap") or {}).get("steps_per_sec")
    if on and off:
        _emit(f"{size}_zero3_overlap_speedup_x", on / off,
              "x (overlap-on over overlap-off wall clock)",
              {**shared,
               "steps_per_sec_overlap_on": on,
               "steps_per_sec_overlap_off": off,
               "note": "double-buffered AG-prefetch vs serial-gather "
                       "control; XLA:CPU dispatches synchronously so "
                       "~1.0x here only proves both schedules "
                       "compile+run — the overlap win is the armed "
                       "TPU prediction (BASELINE_SELF.json)"}, lines)
    detail = {**shared, "matrix": results}
    if errors:
        detail["errors"] = dict(errors)
    if D <= 1:
        detail["note"] = (f"single-device window: shard_update/bucket "
                          f"A/Bs need a multi-device mesh — armed for "
                          f"a bigger window")
    _emit(f"{size}_knob_ab_matrix", float(len(results)), "configs",
          detail, lines)


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--real", action="store_true",
                   help="use the default backend (capture-window mode); "
                        "default forces a virtual CPU mesh")
    p.add_argument("--devices", type=int, default=4,
                   help="forced-CPU-mesh size (ignored with --real)")
    p.add_argument("--json", default="",
                   help="write the full record here (BENCH_lm_* artifact)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--seq_len", type=int, default=128)
    # Throughput + MFU instrument.
    p.add_argument("--throughput_size", default="lm_small")
    p.add_argument("--batch_per_chip", type=int, default=4)
    p.add_argument("--unroll", type=int, default=4)
    p.add_argument("--steps", type=int, default=8)
    p.add_argument("--repeats", type=int, default=2)
    p.add_argument("--skip_throughput", action="store_true")
    # Knob A/B matrix.
    p.add_argument("--size", default="lm_base",
                   help="A/B-matrix model size (lm_base = where the "
                        "knobs bind)")
    p.add_argument("--ab_batch_per_chip", type=int, default=1)
    p.add_argument("--ab_unroll", type=int, default=1)
    p.add_argument("--ab_steps", type=int, default=2,
                   help="measured steps per A/B config (0 = compile-only "
                        "accounting: memory + layout + schedule)")
    p.add_argument("--ab_repeats", type=int, default=1)
    p.add_argument("--knobs", default="",
                   help="comma-separated subset of "
                        f"{_ALL_KNOBS} (default: all)")
    p.add_argument("--ab_timed_knobs",
                   default="base,remat,bucket,zero1,zero3,zero3_nooverlap",
                   help="configs that also get a measured rate; the "
                        "constraint-form shard_update is compile-only by "
                        "default on the CPU mesh (measured at lm_tiny: "
                        "XLA:CPU's partitioner collapses it ~200x, so a "
                        "timed lm_base point would cost minutes to state "
                        "a fact the small-scale number already pins — "
                        "its MEASURED claim here is the layout bytes)")
    p.add_argument("--skip_ab", action="store_true")
    args = p.parse_args(argv)
    args.knobs = [k for k in args.knobs.split(",") if k]
    args.ab_timed_knobs = [k for k in args.ab_timed_knobs.split(",") if k]
    for k in args.knobs + args.ab_timed_knobs:
        if k not in _ALL_KNOBS:
            p.error(f"unknown knob {k!r} (one of {_ALL_KNOBS})")

    if not args.real:
        import jax

        from distributedtensorflowexample_tpu.compat import (
            cpu_collective_flags, set_num_cpu_devices)
        if "collective_call_terminate" not in os.environ.get("XLA_FLAGS",
                                                             ""):
            os.environ["XLA_FLAGS"] = (
                os.environ.get("XLA_FLAGS", "")
                + cpu_collective_flags(warn_s=120, terminate_s=1800))
        for knob, value in (("jax_platforms", "cpu"),
                            ("jax_cpu_enable_async_dispatch", False)):
            try:
                jax.config.update(knob, value)
            except RuntimeError:
                break
        else:
            try:
                set_num_cpu_devices(args.devices)
            except RuntimeError:
                pass
    else:
        # bench.py's probe loop (the bench_profile/bench_collectives
        # precedent): CPU-fallback assert, TERM-grace-KILL on a hung
        # probe child, jittered retries, sentinel on a dead backend.
        import bench
        ok, attempts = bench._wait_for_backend()
        if not ok:
            _sentinel(args, attempts)
            return 0

    import jax

    from distributedtensorflowexample_tpu.obs import ledger as obs_ledger
    from distributedtensorflowexample_tpu.obs import recorder as obs_recorder
    from distributedtensorflowexample_tpu.obs import serve as obs_serve
    from distributedtensorflowexample_tpu.parallel import make_mesh

    obs_recorder.maybe_install()
    obs_ledger.maybe_begin("bench_lm", config=vars(args))
    obs_serve.maybe_start()
    mesh = make_mesh()
    platform = jax.default_backend()
    lines: list = []
    errors: dict = {}
    with mesh:
        if not args.skip_throughput:
            try:
                run_throughput(args, mesh, platform, lines, errors)
            except Exception as e:
                errors["throughput"] = repr(e)
                traceback.print_exc()
        if not args.skip_ab:
            try:
                run_ab_matrix(args, mesh, platform, lines, errors)
            except Exception as e:
                errors["ab_matrix"] = repr(e)
                traceback.print_exc()
    if args.json:
        # JSON LINES (bench.py's stdout dialect): that is what
        # tools/bench_ratchet.py's record loader parses, so the lm
        # family ratchets like the headline family.
        meta = {"metric": "lm_bench_meta", "value": float(len(lines)),
                "unit": "lines", "vs_baseline": 1.0,
                "detail": {"family": "BENCH_lm", "platform": platform,
                           "forced_cpu_mesh": not args.real,
                           "provisional": True,   # meta, not a measurement
                           "errors": errors,
                           "note": ("CPU-mesh numbers calibrate layouts/"
                                    "schedules and arm chip predictions; "
                                    "never read as chip throughput"
                                    if platform == "cpu" else
                                    "capture-window record")}}
        with open(args.json, "w") as f:
            for rec in lines + [meta]:
                f.write(json.dumps(rec) + "\n")
        print(f"bench_lm: wrote {args.json}", file=sys.stderr, flush=True)
    obs_ledger.end_global(rc=0, errors=errors or None)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
