"""Weak-scaling harness — sync-SGD scaling efficiency 1 -> N chips.

The secondary contract metric (BASELINE.json "metric": "sync-SGD scaling
efficiency 1->8 chips"; BASELINE.md target >= 90%).  Weak scaling: fixed
per-chip batch, growing global batch — ideal scaling keeps global steps/sec
constant as devices are added, so

    efficiency(N) = steps_per_sec(N submesh) / steps_per_sec(1 submesh)

Runs the REAL pjit/psum training step (parallel/sync.py) over 1/2/4/8-device
submeshes of whatever is available:

  * real multi-chip hardware -> the contract numbers (run with --real);
  * this environment (one real chip / CI) -> the identical program on an
    8-virtual-device CPU mesh: correctness + overhead trend + the HLO
    collective accounting, so the harness is driver-runnable today and
    chip-ready the day multi-chip hardware appears.

Also reports per-step collective traffic parsed from each submesh's
compiled HLO (op counts + bytes of all-reduce / all-gather /
reduce-scatter / collective-permute / all-to-all) — on a 1-D data mesh the
expected shape is ONE fused gradient all-reduce of ~|params| f32 bytes.

Emits one JSON line per device count and a final summary line
``{"metric": "<mode>_sgd_weak_scaling", ...}``.

``--mode async`` runs the config-2 local-SGD step instead: each device
steps its own virtual worker and the worker average all-reduces only every
``--async_period`` steps, so the sustained collective bytes per step are
the sync mode's divided by the period (reported as
``amortized_bytes_per_step``) — the communication-scaling advantage the
async path buys at the price of bounded staleness.
"""

from __future__ import annotations

import argparse
import json
import math
import re

_DTYPE_BYTES = {"f64": 8, "f32": 4, "bf16": 2, "f16": 2, "s64": 8,
                "s32": 4, "u64": 8, "u32": 4, "s16": 2, "u16": 2,
                "s8": 1, "u8": 1, "pred": 1, "f8e4m3fn": 1, "f8e5m2": 1}

_COLLECTIVES = ("all-reduce", "all-gather", "reduce-scatter",
                "collective-permute", "all-to-all")


def collective_traffic(hlo_text: str) -> dict:
    """Sum output bytes of every collective op in an HLO module text.

    An HLO line reads ``%name = f32[256,10]{1,0} all-reduce(...)`` (or a
    tuple of shapes for variadic all-reduce); we account every
    ``dtype[dims]`` appearing before the op token on such lines.
    """
    shape_re = re.compile(r"([a-z0-9]+)\[([0-9,]*)\]")
    out: dict = {op: {"count": 0, "bytes": 0} for op in _COLLECTIVES}
    for line in hlo_text.splitlines():
        for op in _COLLECTIVES:
            token = f" {op}("
            if token in line and "=" in line:
                head = line.split(token)[0].split("=", 1)[1]
                total = 0
                for dtype, dims in shape_re.findall(head):
                    if dtype not in _DTYPE_BYTES:
                        continue
                    n = math.prod(int(d) for d in dims.split(",") if d) \
                        if dims else 1
                    total += n * _DTYPE_BYTES[dtype]
                out[op]["count"] += 1
                out[op]["bytes"] += total
                break
    return {op: v for op, v in out.items() if v["count"]}


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--real", action="store_true",
                        help="use the real default backend's devices "
                             "(multi-chip hardware); default is an "
                             "8-virtual-device CPU mesh")
    parser.add_argument("--max_devices", type=int, default=8)
    parser.add_argument("--batch_per_chip", type=int, default=64)
    parser.add_argument("--unroll", type=int, default=16)
    parser.add_argument("--steps", type=int, default=64,
                        help="measured steps per repeat (3 repeats)")
    parser.add_argument("--mode", choices=("sync", "async"), default="sync",
                        help="sync = one gradient all-reduce per step; "
                             "async = local-SGD (config 2), whose worker "
                             "average all-reduces only every "
                             "--async_period steps — the per-step "
                             "collective bytes divide by the period")
    parser.add_argument("--async_period", type=int, default=8)
    args = parser.parse_args()
    if args.mode == "async" and args.async_period < 1:
        parser.error(f"--async_period must be >= 1, got {args.async_period}")

    import jax
    if not args.real:
        # Must run before first backend use (this image's sitecustomize
        # force-registers the axon platform over JAX_PLATFORMS, so the
        # in-process config route is the only one that works).
        import os

        from distributedtensorflowexample_tpu.compat import (
            cpu_collective_flags, set_num_cpu_devices)
        if "collective_call_terminate" not in os.environ.get("XLA_FLAGS", ""):
            os.environ["XLA_FLAGS"] = (
                os.environ.get("XLA_FLAGS", "")
                + cpu_collective_flags(warn_s=120, terminate_s=600))
        for knob, value in (("jax_platforms", "cpu"),
                            ("jax_cpu_enable_async_dispatch", False)):
            try:
                jax.config.update(knob, value)
            except RuntimeError:
                break
        else:
            try:
                set_num_cpu_devices(args.max_devices)
            except RuntimeError:
                pass

    import optax

    from distributedtensorflowexample_tpu.config import RunConfig
    from distributedtensorflowexample_tpu.data.synthetic import make_synthetic
    from distributedtensorflowexample_tpu.engine import Engine, RunSpec
    from distributedtensorflowexample_tpu.parallel import make_mesh
    # Same warmup/best-of-repeats measurement the main bench uses.
    from bench import _measure

    # Run ledger + live scrape (env-gated; OBS_LEDGER / OBS_HTTP_PORT).
    from distributedtensorflowexample_tpu.obs import ledger as obs_ledger
    from distributedtensorflowexample_tpu.obs import serve as obs_serve
    obs_ledger.maybe_begin("bench_scaling", config=vars(args))
    obs_serve.maybe_start()

    avail = len(jax.devices())
    counts = [n for n in (1, 2, 4, 8, 16, 32) if n <= min(avail,
                                                          args.max_devices)]
    backend = jax.default_backend()
    results = {}
    for n in counts:
        mesh = make_mesh(n)
        global_batch = args.batch_per_chip * n

        def input_fn(cfg, split, _gb=global_batch):
            return make_synthetic(_gb * args.unroll * 2, (28, 28, 1),
                                  10, seed=0)

        def optimizer_fn(cfg, _mesh, wrap_shard_update):
            return optax.sgd(0.05, momentum=0.9)

        # The config-1/2 workloads as Engine declarations
        # (engine/engine.py): the Engine wires the same indexed
        # sync/async step builders run_training measures.
        spec = RunSpec(
            model="mnist_cnn", dataset="mnist",
            config=RunConfig(batch_size=args.batch_per_chip, seed=0,
                             sync_mode=args.mode,
                             async_period=args.async_period),
            input_fn=input_fn, optimizer_fn=optimizer_fn)
        built = Engine(spec).build(mesh=mesh, unroll=args.unroll)
        step, ds, state = built.step, built.ds, built.state
        with mesh:
            # Per-step collective traffic from a SINGLE-step compile: in
            # the unrolled program the collectives live inside the scan
            # body (once in the module text, executed every sub-step), so
            # the one-step module is the honest per-step accounting.
            # peek, not next: lowering must not advance the perm ring
            # ahead of state.step (the unroll-1 build's own dataset is
            # discarded — only its compiled step is inspected).
            per_step = collective_traffic(
                Engine(spec).build(mesh=mesh, unroll=1).step
                .lower(state, ds.peek()).compile().as_text())
            best, rates, _ = _measure(step, ds, state, args.steps,
                                      args.unroll, warmup_calls=1)
        results[n] = {"steps_per_sec": best,
                      "repeats": rates,
                      "collectives_per_step": per_step}
        line = {
            "devices": n, "backend": backend, "mode": args.mode,
            "global_batch": global_batch,
            "steps_per_sec": round(best, 2),
            "repeats": rates,
            "collectives_per_step": per_step,
        }
        if args.mode == "async":
            # The worker-average all-reduce sits in a lax.cond branch: it
            # appears once in the module text but executes only every
            # --async_period-th step, so the sustained wire cost is the
            # parsed bytes divided by the period — local SGD's whole
            # communication advantage over per-step sync.
            line["amortized_bytes_per_step"] = {
                op: round(v["bytes"] / args.async_period)
                for op, v in per_step.items()}
            # The parsed all-reduce bucket also holds the scalar
            # loss/accuracy metrics psum, which runs EVERY step (not
            # cond-gated), so the division is exact only for the worker
            # average; the error is the ~8-byte metrics psum per step.
            line["amortized_note"] = (
                "exact for the cond-gated worker average only; the "
                "every-step scalar-metrics psum bytes are amortized too")
        print(json.dumps(line), flush=True)

    base = results[counts[0]]["steps_per_sec"]
    efficiency = {str(n): round(results[n]["steps_per_sec"] / base, 4)
                  for n in counts}
    print(json.dumps({
        "metric": f"{args.mode}_sgd_weak_scaling",
        "value": efficiency[str(counts[-1])],
        "unit": f"efficiency_1_to_{counts[-1]}",
        "vs_baseline": 1.0,
        "detail": {"backend": backend, "mode": args.mode,
                   "efficiency": efficiency,
                   "batch_per_chip": args.batch_per_chip,
                   "note": ("real-chip contract numbers require multi-chip "
                            "hardware (--real); virtual CPU meshes share "
                            "one host's cores, so their efficiency reflects "
                            "per-step overhead trend only")},
    }), flush=True)
    obs_ledger.end_global(rc=0)


if __name__ == "__main__":
    main()
