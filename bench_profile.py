"""On-chip ResNet-20 profiling + MFU attribution (VERDICT r2 item 2).

Decomposes the flagship workload's throughput in ONE process/window (the
shared chip's ~10-20x cross-window variance makes cross-window deltas
meaningless, BASELINE_SELF.json note):

  measured(augment)    the contract config-4 path (crop/flip on device)
  measured(no augment) same fused gather/perm-ring path, augment off
  roofline             scanned fixed resident batch — no gather/augment/
                       per-call dispatch (bench._roofline_probe)

  augment share   = 1 - rate_aug / rate_noaug
  input+dispatch  = 1 - rate_noaug / rate_roofline
  compute quality = rate_roofline vs the analytic MXU ceiling (printed as
                    mfu_roofline; the residual is conv MXU underfill at
                    widths 16/32/64 + BN/elementwise HBM traffic —
                    attributed by the trace)

Also captures a jax.profiler trace of a steady-state window (NOT the
compile) when the backend supports it; emits one JSON line per variant,
same shape as bench.py lines.

Usage (on the chip):  python bench_profile.py --unroll 195
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import time
import traceback

import jax

import bench
from distributedtensorflowexample_tpu.obs import recorder as obs_recorder
from distributedtensorflowexample_tpu.obs.trace import span


def _emit(metric: str, value: float, detail: dict) -> None:
    print(json.dumps({"metric": metric, "value": round(value, 2),
                      "unit": "steps/sec/chip", "vs_baseline": 1.0,
                      "detail": detail}), flush=True)


# ResNet-20 at CIFAR shapes is bandwidth-bound (arithmetic intensity
# ~4 FLOP/B vs the v5e ridge ~240), so the honest roofline is
# min(peak_flops/F, hbm_bw/B) — the MFU number alone misattributes a
# bandwidth ceiling as 'low utilization'.  Cost probing shares bench's
# one implementation (bench._cost_per_step).


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--unroll", type=int, default=195,
                    help="fused steps per call (195 = 1 epoch at batch 256)")
    ap.add_argument("--steps", type=int, default=390)
    ap.add_argument("--batch_per_chip", type=int, default=256)
    ap.add_argument("--trace_dir", default="/tmp/resnet_trace")
    ap.add_argument("--skip_trace", action="store_true")
    ap.add_argument("--roofline_length", type=int, default=128,
                    help="scanned steps per roofline repeat (CI shrinks "
                         "this: 128 ResNet steps x 4 runs take tens of "
                         "minutes on the virtual CPU mesh)")
    args = ap.parse_args()

    # Under a supervised capture (or OBS_FLIGHT=1), leave a per-phase
    # flight postmortem (the spans below share OBS_PHASE with the
    # capture journal's task).  sigterm default ON: unlike bench.py this
    # process has no record-survival handler of its own, so without the
    # chained dump a supervisor wall-timeout TERM would kill it with no
    # postmortem at all.
    obs_recorder.maybe_install()
    # Run ledger + live scrape (env-gated; OBS_LEDGER / OBS_HTTP_PORT).
    from distributedtensorflowexample_tpu.obs import ledger as obs_ledger
    from distributedtensorflowexample_tpu.obs import serve as obs_serve
    obs_ledger.maybe_begin("bench_profile", config=vars(args))
    obs_serve.maybe_start()

    probe_attempts: list = []

    def emit_unavailable(why: str) -> None:
        print(json.dumps({
            "metric": "resnet20_attribution", "value": 0.0,
            "unit": "unavailable", "vs_baseline": 0.0,
            "detail": {"error": why[:500],
                       "probe_attempts": probe_attempts[-8:]}}), flush=True)

    # Same outage resilience as bench.main: probe-with-retries before the
    # in-process init, the init itself sentinel-guarded, and a watchdog
    # for calls that block without raising after the backend dies mid-run
    # (round-3 failure shape).
    reachable, attempts = bench._wait_for_backend()
    probe_attempts.extend(attempts)
    if not reachable:
        emit_unavailable("TPU backend unreachable after probe retries "
                         f"(budget {bench.RETRY_BUDGET_S:.0f}s)")
        # A reported sentinel is a clean outcome in the ledger too —
        # rc=None stays reserved for runs that never got to say so.
        obs_ledger.end_global(rc=0, note="backend unreachable sentinel")
        return
    if bench._cpu_platform():
        # CPU-platform runs (CI / virtual mesh) are legitimately slow —
        # the --roofline_length help text warns default sizes take tens
        # of minutes there — and can't wedge on a tunnel; don't arm.
        # Platform check only (NOT _cpu_pinned): a real TPU run with
        # BENCH_SKIP_PROBE=1 can still wedge mid-profile and, in the
        # detached capture path, would hang forever unwatched.
        watchdog_done = None
    else:
        watchdog_done = bench._arm_watchdog(
            bench.TOTAL_BUDGET_S, lambda: emit_unavailable(
                f"watchdog: profiling exceeded {bench.TOTAL_BUDGET_S:.0f}s "
                "— a call blocked without raising (backend presumed lost "
                "mid-run); lines above are valid completed measurements"))

    from distributedtensorflowexample_tpu.parallel import make_mesh
    try:
        mesh = make_mesh()
    except Exception as e:
        emit_unavailable(f"TPU backend unavailable: {e!r}")
        if watchdog_done is not None:
            watchdog_done.set()
        obs_ledger.end_global(rc=0, note="backend-unavailable sentinel")
        return
    n = mesh.size
    rates = {}
    errors = {}

    def attempt(name, fn):
        """Per-stage fault isolation, like bench.main: a tunnel drop in
        one variant must not eat the lines the earlier variants already
        paid for (nor the attribution summary below)."""
        try:
            fn()
        except Exception as e:
            errors[name] = repr(e)
            traceback.print_exc()

    HBM_BW = float(os.environ.get("TPU_HBM_BW", 819e9))   # v5e bytes/s

    def run_variant(tag, aug):
        with span(f"profile_{tag}", unroll=args.unroll):
            return _run_variant_inner(tag, aug)

    def _run_variant_inner(tag, aug):
        from distributedtensorflowexample_tpu.utils.profiling import (
            cost_and_bytes_audit)
        step, ds, state, u = bench._make(
            "resnet20", "cifar10", args.batch_per_chip, args.unroll,
            mesh, augment=aug, lr=0.1)
        # One lower+compile serves both the aggregate cost keys AND the
        # per-op bytes table (tools/bytes_audit.py's decomposition): the
        # round-5 record carried only the aggregate, which over-counts
        # the fused resident-split gather by the whole split array —
        # effective bytes re-price it at rows-touched, and that is the
        # honest denominator for the bandwidth roofline below.
        cost, audit = cost_and_bytes_audit(step, (state, ds.peek()),
                                           unroll=u, top_k=8)
        best, reps, state = bench._measure(step, ds, state, args.steps, u)
        rates[tag] = best
        flops, nbytes = cost.get("flops"), cost.get("bytes_accessed")
        detail = {"repeats": reps, "unroll": u, "flops_per_step": flops,
                  "bytes_per_step": nbytes}
        if flops:
            detail["mfu"] = round(flops * best / n / bench.PEAK_FLOPS, 5)
        if flops and nbytes:
            # Compute-vs-bandwidth attribution: which wall does this
            # program's arithmetic intensity put it against?
            detail["arith_intensity_flop_per_byte"] = round(
                flops / nbytes, 2)
            detail["bw_roofline_steps_per_sec"] = round(HBM_BW / nbytes, 1)
            detail["mfu_ceiling_at_bw"] = round(
                (HBM_BW / nbytes) * flops / bench.PEAK_FLOPS, 5)
        if audit:
            detail["bytes_audit"] = audit
            nbytes_eff = audit.get("bytes_effective_per_step")
            if flops and nbytes_eff:
                detail["arith_intensity_effective"] = round(
                    flops / nbytes_eff, 2)
                detail["bw_roofline_effective_steps_per_sec"] = round(
                    HBM_BW / nbytes_eff, 1)
                detail["mfu_ceiling_at_bw_effective"] = round(
                    (HBM_BW / nbytes_eff) * flops / bench.PEAK_FLOPS, 5)
        _emit(f"resnet20_profile_{tag}", best / n, detail)
        return step, ds, state, u

    with mesh:
        for tag, aug in (("augment", "cifar"), ("no_augment", "none")):
            box = []
            attempt(tag, lambda: box.append(run_variant(tag, aug)))
            if not box:
                continue
            step, ds, state, u = box[0]

            if tag == "augment" and not args.skip_trace:
                # Trace ONE steady-state call (state is warm, program
                # cached) — the trace shows the op-level time breakdown
                # the MFU number alone can't give.
                try:
                    jax.profiler.start_trace(args.trace_dir)
                    try:
                        with span("trace_window", unroll=u):
                            t0 = time.perf_counter()
                            state, m = step(state, next(ds))
                            jax.block_until_ready(m)
                            dt = time.perf_counter() - t0
                    finally:
                        # Never leave the profiler running: it would skew
                        # the no_augment + roofline rates measured next.
                        jax.profiler.stop_trace()
                    files = glob.glob(os.path.join(
                        args.trace_dir, "**", "*"), recursive=True)
                    nbytes = sum(os.path.getsize(f) for f in files
                                 if os.path.isfile(f))
                    _emit("resnet20_traced_window", u / dt / n,
                          {"trace_dir": args.trace_dir,
                           "trace_files": len(files),
                           "trace_bytes": nbytes,
                           "steps_in_window": u})
                except Exception as e:
                    traceback.print_exc()
                    print(json.dumps({
                        "metric": "resnet20_traced_window",
                        "value": 0.0, "unit": "unavailable",
                        "vs_baseline": 0.0,
                        "detail": {"error": f"profiler failed: {e!r}"[:400]},
                    }), flush=True)

        def run_roofline():
            with span("roofline", length=args.roofline_length):
                roof = bench._roofline_probe(mesh, args.batch_per_chip,
                                             length=args.roofline_length,
                                             model_name="resnet20",
                                             sample=(32, 32, 3), lr=0.1)
            rates["roofline"] = max(roof)
            _emit("resnet20_roofline", max(roof) / n, {"repeats": roof})

        attempt("roofline", run_roofline)

    # Attribution from whatever survived — partial shares still tell the
    # story of the window (errors ride along for the missing pieces).
    detail = {}
    if "augment" in rates and "no_augment" in rates:
        detail["augment_share"] = round(
            1 - rates["augment"] / rates["no_augment"], 4)
    if "no_augment" in rates and "roofline" in rates:
        detail["input_dispatch_share"] = round(
            1 - rates["no_augment"] / rates["roofline"], 4)
    if errors:
        detail["errors"] = errors
    if detail or ("augment" in rates and "roofline" in rates):
        print(json.dumps({
            "metric": "resnet20_attribution",
            "value": (round(rates["augment"] / rates["roofline"], 4)
                      if "augment" in rates and "roofline" in rates
                      else 0.0),
            "unit": "measured/roofline", "vs_baseline": 1.0,
            "detail": detail}), flush=True)
    if watchdog_done is not None:
        watchdog_done.set()
    obs_ledger.end_global(rc=0, errors=errors or None)


if __name__ == "__main__":
    main()
